"""paddle_tpu.sparse — sparse tensors.

Parity: `paddle.sparse` (`python/paddle/incubate/sparse/` in the snapshot:
SparseCooTensor/SparseCsrTensor, `paddle/phi/core/sparse_coo_tensor.h`)
over `jax.experimental.sparse` (BCOO — XLA-lowerable sparse ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor


class SparseTensor(Tensor):
    """Tensor holding a BCOO; densifies lazily when a dense op touches it
    (so inherited Tensor methods keep working — a dense fallback, like the
    reference's coo→dense kernel fallbacks)."""

    __slots__ = ("_bcoo", "_dense_cache", "_values_ref")

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        self._dense_cache = None
        super().__init__(jnp.zeros((), jnp.float32),
                         stop_gradient=stop_gradient)
        self._dense_cache = None  # discard the placeholder written above

    @property
    def _data(self):
        if self._dense_cache is None:
            vref = getattr(self, "_values_ref", None)
            if vref is not None and not vref.stop_gradient:
                from ..core import autograd as _ag
                if not _ag.is_grad_enabled():
                    # no_grad access: densify WITHOUT caching, so a
                    # later grad-enabled access can still adopt the
                    # grad node (caching here would permanently sever
                    # the conv/bn weight gradients)
                    return self._bcoo.todense()
                # densify THROUGH the autograd graph and adopt the
                # resulting grad node, so inherited dense Tensor ops
                # consuming this sparse tensor keep gradients flowing
                # into the sparse conv/bn parameters (instead of
                # recording this tensor as a grad-less leaf)
                dense = self.to_dense()
                self._dense_cache = dense._data
                self._grad_node = dense._grad_node
                self._out_slot = dense._out_slot
            else:
                self._dense_cache = self._bcoo.todense()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        self._dense_cache = value

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def to_dense(self):
        vref = getattr(self, "_values_ref", None)
        if vref is not None and not vref.stop_gradient:
            # differentiable densify: grads flow back into the values
            # produced by sparse conv/bn layers (conv.py _wrap_out)
            from ..core import dispatch
            idx = self._bcoo.indices
            shape = tuple(self._bcoo.shape)

            def fn(v):
                return jnp.zeros(shape, v.dtype).at[
                    tuple(idx[:, i] for i in range(idx.shape[1]))].add(v)
            return dispatch.apply("sparse_to_dense", fn, (vref,))
        return Tensor(self._bcoo.todense())

    def values(self):
        vref = getattr(self, "_values_ref", None)
        return vref if vref is not None else Tensor(self._bcoo.data)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def nnz(self):
        return int(self._bcoo.nse)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, "
                f"nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """indices: [ndim, nnz] (paddle layout)."""
    idx = as_tensor(indices)._data
    vals = as_tensor(values, dtype=dtype)._data
    idx_t = jnp.swapaxes(idx, 0, 1).astype(jnp.int32)  # [nnz, ndim]
    if shape is None:
        shape = tuple(int(i) for i in (idx.max(axis=1) + 1).tolist())
    bcoo = jsparse.BCOO((vals, idx_t), shape=tuple(int(s) for s in shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    crows = np.asarray(as_tensor(crows).numpy())
    cols = np.asarray(as_tensor(cols).numpy())
    vals = as_tensor(values, dtype=dtype)._data
    # expand crows to row indices -> BCOO
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


def matmul(x, y):
    """sparse @ dense — BCOO dot_general, no densification."""
    if isinstance(x, SparseTensor):
        yd = as_tensor(y)._data
        return Tensor(x._bcoo @ yd)
    raise TypeError("sparse.matmul expects a SparseTensor lhs")


def mv(x, vec):
    """sparse matrix @ dense vector."""
    return matmul(x, vec)


def masked_matmul(x, y, mask):
    """dense @ dense evaluated ONLY at `mask`'s nonzero positions
    (reference sparse.masked_matmul / SDDMM): out is sparse with mask's
    pattern. Computes a gathered row·col dot per nonzero — O(nnz·k), not
    O(n·m·k)."""
    xd = as_tensor(x)._data
    yd = as_tensor(y)._data
    idx = mask._bcoo.indices  # [nnz, 2]
    rows = xd[idx[:, 0], :]          # [nnz, k]
    cols = yd[:, idx[:, 1]].T        # [nnz, k]
    vals = jnp.sum(rows * cols, axis=-1).astype(xd.dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


def add(x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor(x._bcoo + y._bcoo)
    raise TypeError("sparse.add expects SparseTensors")


def _unary_on_values(fn, x: "SparseTensor") -> "SparseTensor":
    """Value-space op: touches only the nnz values (real sparse compute,
    like the reference's sparse unary kernels
    `paddle/phi/kernels/sparse/unary_kernel.h`). Autograd-linked values
    (sparse conv/bn outputs) stay linked so grads flow through chains
    of sparse ops."""
    b = x._bcoo
    vref = getattr(x, "_values_ref", None)
    if vref is not None and not vref.stop_gradient:
        from ..core import dispatch
        from .conv import _wrap_out
        out_vals = dispatch.apply("sparse_unary", fn, (vref,))
        return _wrap_out(out_vals, np.asarray(b.indices),
                         tuple(b.shape))
    return SparseTensor(jsparse.BCOO((fn(b.data), b.indices),
                                     shape=b.shape))


def relu(x):
    return _unary_on_values(lambda v: jnp.maximum(v, 0), x)


def sin(x):
    return _unary_on_values(jnp.sin, x)


def tanh(x):
    return _unary_on_values(jnp.tanh, x)


def sqrt(x):
    return _unary_on_values(jnp.sqrt, x)


def abs(x):  # noqa: A001 - paddle API name
    return _unary_on_values(jnp.abs, x)


def neg(x):
    return _unary_on_values(jnp.negative, x)


def pow(x, factor):  # noqa: A001 - paddle API name
    return _unary_on_values(lambda v: jnp.power(v, factor), x)


def scale(x, scale_, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return _unary_on_values(lambda v: v * scale_ + bias, x)
    return _unary_on_values(lambda v: (v + bias) * scale_, x)


def cast(x, index_dtype=None, value_dtype=None):
    from ..core import dtype as dtype_mod
    b = x._bcoo
    vals = b.data if value_dtype is None else \
        b.data.astype(dtype_mod.convert_dtype(value_dtype))
    idx = b.indices if index_dtype is None else \
        b.indices.astype(dtype_mod.convert_dtype(index_dtype))
    return SparseTensor(jsparse.BCOO((vals, idx), shape=b.shape))


def multiply(x, y):
    """elementwise sparse*sparse (same pattern) or sparse*scalar."""
    if isinstance(y, (int, float)):
        return _unary_on_values(lambda v: v * y, x)
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor(jsparse.bcoo_multiply_sparse(x._bcoo,
                                                         y._bcoo))
    raise TypeError("sparse.multiply expects sparse operands or a scalar")


def transpose(x, perm):
    return SparseTensor(jsparse.bcoo_transpose(x._bcoo,
                                               permutation=tuple(perm)))


def coalesce(x):
    """Sum duplicate coordinates (reference CoalesceKernel)."""
    return SparseTensor(jsparse.bcoo_sum_duplicates(x._bcoo))


def softmax(x, axis=-1):
    """Softmax over the SPARSE pattern only (the reference's sparse
    softmax semantics: missing entries are -inf, i.e. excluded), for
    N-D COO along any axis — including hybrid tensors whose trailing
    dims are dense (values [nnz, ...]). Sparse-axis softmax groups
    entries by every OTHER sparse index (segment max/sum, O(nnz));
    dense-axis softmax is a plain softmax over that value axis. Keeps
    the autograd link of values-linked tensors."""
    from ..core import dispatch
    b = x._bcoo
    nd = len(b.shape)
    ax = axis % nd
    n_sparse = b.indices.shape[1]
    vref = getattr(x, "_values_ref", None)
    linked = vref is not None and not vref.stop_gradient
    vals_in = vref if linked else Tensor(b.data)

    if ax >= n_sparse:
        # dense trailing dim: softmax along the matching value axis
        vax = ax - n_sparse + 1

        def fn(v):
            m = jnp.max(v, axis=vax, keepdims=True)
            e = jnp.exp(v - m)
            return e / jnp.sum(e, axis=vax, keepdims=True)
    else:
        # segment ids over the OTHER sparse index columns, built on
        # host in int64 (jnp would silently be int32 with x64 off and
        # overflow the row-major flatten for large shapes)
        idx_np = np.asarray(b.indices, np.int64)
        seg_np = np.zeros(idx_np.shape[0], np.int64)
        for i in range(n_sparse):
            if i == ax:
                continue
            seg_np = seg_np * int(b.shape[i]) + idx_np[:, i]
        _, seg_c_np = np.unique(seg_np, return_inverse=True)
        seg_c = jnp.asarray(seg_c_np)
        n_seg = int(seg_c_np.max()) + 1 if len(seg_c_np) else 0

        def fn(v):
            rmax = jax.ops.segment_max(v, seg_c, num_segments=n_seg)
            e = jnp.exp(v - rmax[seg_c])
            rsum = jax.ops.segment_sum(e, seg_c, num_segments=n_seg)
            return e / rsum[seg_c]

    out_vals = dispatch.apply("sparse_softmax", fn, (vals_in,))
    if linked:
        from .conv import _wrap_out
        return _wrap_out(out_vals, np.asarray(b.indices),
                         tuple(b.shape))
    return SparseTensor(jsparse.BCOO((out_vals._data, b.indices),
                                     shape=b.shape))


def is_sparse(x):
    return isinstance(x, SparseTensor)


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class _SparseSoftmax:
    def __init__(self, axis=-1):
        self.axis = axis

    def __call__(self, x):
        return softmax(x, self.axis)


from . import conv as _conv_mod  # noqa: E402
from .conv import (conv3d, subm_conv3d, max_pool3d,  # noqa: F401,E402
                   Conv3D, SubmConv3D, MaxPool3D, BatchNorm)


class _SparseFunctional:
    """paddle.sparse.nn.functional namespace."""
    conv3d = staticmethod(conv3d)
    subm_conv3d = staticmethod(subm_conv3d)
    max_pool3d = staticmethod(max_pool3d)

    @staticmethod
    def relu(x):
        return relu(x)

    @staticmethod
    def softmax(x, axis=-1):
        return softmax(x, axis)


class nn:  # namespace shim: paddle.sparse.nn.*
    ReLU = _SparseReLU
    Softmax = _SparseSoftmax
    Conv3D = Conv3D
    SubmConv3D = SubmConv3D
    MaxPool3D = MaxPool3D
    BatchNorm = BatchNorm
    functional = _SparseFunctional


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """`sparse/addmm_kernel.h` — beta*input + alpha*(x @ y), x sparse."""
    from ..ops._helpers import as_tensor as _as_dense
    inp = _as_dense(input)
    prod = matmul(x, y)
    from ..core.tensor import Tensor as _T
    return _T(beta * inp._data + alpha * _as_dense(prod)._data)


def mask_as(x, mask, name=None):
    """`sparse/mask_kernel.h` — take dense x's values at the sparse
    pattern of `mask`, producing a SparseTensor."""
    from ..ops._helpers import as_tensor as _as_dense
    xd = _as_dense(x)._data
    idx = mask.indices()._data if hasattr(mask, "indices") else None
    vals = xd[tuple(idx[i] for i in range(idx.shape[0]))]
    return sparse_coo_tensor(idx, vals, shape=list(xd.shape))
