"""paddle_tpu.sparse — sparse tensors.

Parity: `paddle.sparse` (`python/paddle/incubate/sparse/` in the snapshot:
SparseCooTensor/SparseCsrTensor, `paddle/phi/core/sparse_coo_tensor.h`)
over `jax.experimental.sparse` (BCOO — XLA-lowerable sparse ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor


class SparseTensor(Tensor):
    """Tensor holding a BCOO; densifies lazily when a dense op touches it
    (so inherited Tensor methods keep working — a dense fallback, like the
    reference's coo→dense kernel fallbacks)."""

    __slots__ = ("_bcoo", "_dense_cache")

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        self._dense_cache = None
        super().__init__(jnp.zeros((), jnp.float32),
                         stop_gradient=stop_gradient)
        self._dense_cache = None  # discard the placeholder written above

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._bcoo.todense()
        return self._dense_cache

    @_data.setter
    def _data(self, value):
        self._dense_cache = value

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def values(self):
        return Tensor(self._bcoo.data)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def nnz(self):
        return int(self._bcoo.nse)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, "
                f"nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """indices: [ndim, nnz] (paddle layout)."""
    idx = as_tensor(indices)._data
    vals = as_tensor(values, dtype=dtype)._data
    idx_t = jnp.swapaxes(idx, 0, 1).astype(jnp.int32)  # [nnz, ndim]
    if shape is None:
        shape = tuple(int(i) for i in (idx.max(axis=1) + 1).tolist())
    bcoo = jsparse.BCOO((vals, idx_t), shape=tuple(int(s) for s in shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    crows = np.asarray(as_tensor(crows).numpy())
    cols = np.asarray(as_tensor(cols).numpy())
    vals = as_tensor(values, dtype=dtype)._data
    # expand crows to row indices -> BCOO
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseTensor(bcoo, stop_gradient=stop_gradient)


def matmul(x, y):
    """sparse @ dense."""
    if isinstance(x, SparseTensor):
        yd = as_tensor(y)._data
        return Tensor(x._bcoo @ yd)
    raise TypeError("sparse.matmul expects a SparseTensor lhs")


def add(x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor(x._bcoo + y._bcoo)
    raise TypeError("sparse.add expects SparseTensors")


def is_sparse(x):
    return isinstance(x, SparseTensor)
