"""Sparse 3-D convolution family (Conv3D / SubmConv3D / MaxPool3D /
BatchNorm) on COO tensors.

Parity: `python/paddle/sparse/nn/layer/conv.py:133,268` (Conv3D,
SubmConv3D), `pooling.py:19` (MaxPool3D), `norm.py:23` (BatchNorm) over
the reference's `paddle/phi/kernels/sparse/` conv kernels.

TPU-native re-design: sparsity patterns are data-dependent (dynamic
shapes), so the coordinate algebra — building output coordinates and
the per-kernel-offset (input point, output point) gather/scatter maps —
runs eagerly on host numpy (the reference's rulebook/hashmap step,
`gpu/conv_kernel.cu`'s rulebook build). The FEATURE computation
(gather -> matmul per offset -> segment-sum scatter) runs through the
framework's dispatch so it is autograd-differentiable w.r.t. weights,
bias, and input values, and jit-compiles per sparsity pattern.

Layouts (reference convention): input COO shape [N, D, H, W, C] with
indices [nnz, 4] = (n, d, h, w); kernel [kd, kh, kw, C_in, C_out].
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core import dispatch
from ..core.tensor import Tensor


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _coord_key(coords, spatial):
    """[n, 4] (n, d, h, w) -> unique int64 key."""
    D, H, W = spatial
    return ((coords[:, 0].astype(np.int64) * D + coords[:, 1]) * H +
            coords[:, 2]) * W + coords[:, 3]


_RULEBOOK_CACHE = {}
_RULEBOOK_CACHE_MAX = 32


def build_rulebook(coords_np, spatial_in, kernel, stride, padding, subm):
    """Host-side rulebook (the reference's sparse-conv hashmap step),
    memoized per (sparsity pattern, geometry) — a training loop over a
    static point cloud pays the O(nnz * k^3) numpy work once.

    Returns (out_coords [n_out, 4], out_spatial, rules) where rules is a
    list over kernel offsets of (in_idx, out_idx) index arrays."""
    import hashlib
    ck = (hashlib.blake2b(np.ascontiguousarray(coords_np).tobytes(),
                          digest_size=16).digest(),
          coords_np.shape, spatial_in, kernel, stride, padding, subm)
    hit = _RULEBOOK_CACHE.get(ck)
    if hit is not None:
        return hit
    out = _build_rulebook_impl(coords_np, spatial_in, kernel, stride,
                               padding, subm)
    if len(_RULEBOOK_CACHE) >= _RULEBOOK_CACHE_MAX:
        _RULEBOOK_CACHE.pop(next(iter(_RULEBOOK_CACHE)))
    _RULEBOOK_CACHE[ck] = out
    return out


def _build_rulebook_impl(coords_np, spatial_in, kernel, stride, padding,
                         subm):
    kd, kh, kw = kernel
    sd, sh, sw = stride
    pd, ph, pw = padding
    Din, Hin, Win = spatial_in
    if subm:
        out_spatial = spatial_in
    else:
        out_spatial = ((Din + 2 * pd - kd) // sd + 1,
                       (Hin + 2 * ph - kh) // sh + 1,
                       (Win + 2 * pw - kw) // sw + 1)
    # one pass of the per-offset coordinate algebra, reused for both
    # output-coordinate discovery and rule building
    per_offset = []
    for off in np.ndindex(kd, kh, kw):
        sp = coords_np[:, 1:] + np.array([pd, ph, pw]) - np.array(off)
        ok = (sp % np.array([sd, sh, sw]) == 0).all(1)
        q = sp // np.array([sd, sh, sw])
        ok &= (q >= 0).all(1) & (q < np.array(out_spatial)).all(1)
        per_offset.append((np.nonzero(ok)[0], q))
    if subm:
        out_coords = coords_np
    else:
        cands = [np.concatenate([coords_np[ii, :1], q[ii]], axis=1)
                 for ii, q in per_offset if ii.size]
        if not cands:
            return np.zeros((0, 4), np.int64), out_spatial, []
        allc = np.concatenate(cands, axis=0)
        keys = _coord_key(allc, out_spatial)
        _, first = np.unique(keys, return_index=True)
        out_coords = allc[np.sort(first)]
    out_keys = _coord_key(out_coords, out_spatial)
    order = np.argsort(out_keys)
    sorted_keys = out_keys[order]
    rules = []
    for in_idx, q in per_offset:
        if in_idx.size == 0:
            rules.append((in_idx, in_idx))
            continue
        tgt = np.concatenate([coords_np[in_idx, :1], q[in_idx]], axis=1)
        tkeys = _coord_key(tgt, out_spatial)
        pos = np.searchsorted(sorted_keys, tkeys)
        pos = np.clip(pos, 0, len(sorted_keys) - 1)
        hit = sorted_keys[pos] == tkeys
        rules.append((in_idx[hit], order[pos[hit]]))
    return out_coords.astype(np.int64), out_spatial, rules


def _sparse_values(x):
    """(values Tensor in the autograd graph, coords np, shape)."""
    from . import SparseTensor
    if not isinstance(x, SparseTensor):
        raise TypeError("expected a SparseCooTensor")
    vals = getattr(x, "_values_ref", None)
    if vals is None:
        vals = Tensor(x._bcoo.data)
    return vals, np.asarray(x._bcoo.indices), tuple(x._bcoo.shape)


def _wrap_out(values_t, coords_np, shape):
    """SparseTensor whose values stay LINKED into the autograd graph."""
    from . import SparseTensor
    bcoo = jsparse.BCOO((values_t._data, jnp.asarray(coords_np)),
                        shape=shape)
    out = SparseTensor(bcoo, stop_gradient=values_t.stop_gradient)
    out._values_ref = values_t
    return out


def _conv3d_impl(x, weight, bias, stride, padding, subm):
    vals, coords, shape = _sparse_values(x)
    N, Din, Hin, Win, Cin = shape
    kernel = tuple(weight.shape[:3])
    stride = _triple(stride)
    padding = _triple(padding)
    out_coords, out_spatial, rules = build_rulebook(
        coords, (Din, Hin, Win), kernel, stride, padding, subm)
    n_out = len(out_coords)
    Cout = weight.shape[-1]
    out_shape = (N, *out_spatial, Cout)
    if n_out == 0:
        z = Tensor(jnp.zeros((0, Cout), vals._data.dtype))
        return _wrap_out(z, out_coords, out_shape)
    flat_rules = [(i, r) for i, r in enumerate(rules) if r[0].size]
    in_cat = np.concatenate([r[0] for _, r in flat_rules])
    out_cat = np.concatenate([r[1] for _, r in flat_rules])
    offs = [i for i, r in flat_rules]
    sizes = [r[0].size for _, r in flat_rules]

    def fn(v, w, *b):
        wf = w.reshape(-1, Cin, Cout)
        parts = []
        start = 0
        for oi, sz in zip(offs, sizes):
            idx = in_cat[start:start + sz]
            parts.append(v[idx] @ wf[oi])
            start += sz
        contrib = jnp.concatenate(parts, axis=0)
        out = jax.ops.segment_sum(contrib, jnp.asarray(out_cat),
                                  num_segments=n_out)
        if b:
            out = out + b[0]
        return out

    ins = (vals, weight) + ((bias,) if bias is not None else ())
    out_vals = dispatch.apply("sparse_conv3d", fn, ins)
    return _wrap_out(out_vals, out_coords, out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC"):
    """paddle.sparse.nn.functional.conv3d parity (dilation/groups=1)."""
    if _triple(dilation) != (1, 1, 1) or groups != 1:
        raise NotImplementedError("sparse conv3d: dilation/groups")
    return _conv3d_impl(x, weight, bias, stride, padding, subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None):
    """Submanifold conv: output sparsity == input sparsity (stride must
    be 1 — the submanifold contract). `padding` aligns the kernel
    window like the reference (pass k//2 for the usual centered
    window)."""
    if _triple(stride) != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1")
    if _triple(dilation) != (1, 1, 1) or groups != 1:
        raise NotImplementedError("sparse subm_conv3d: dilation/groups")
    return _conv3d_impl(x, weight, bias, (1, 1, 1), _triple(padding),
                        subm=True)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC"):
    """Sparse max pooling: max over each output cell's PRESENT inputs."""
    vals, coords, shape = _sparse_values(x)
    N, Din, Hin, Win, C = shape
    kernel = _triple(kernel_size)
    stride = _triple(stride if stride is not None else kernel_size)
    padding = _triple(padding)
    out_coords, out_spatial, rules = build_rulebook(
        coords, (Din, Hin, Win), kernel, stride, padding, subm=False)
    n_out = len(out_coords)
    out_shape = (N, *out_spatial, C)
    if n_out == 0:
        return _wrap_out(Tensor(jnp.zeros((0, C), vals._data.dtype)),
                         out_coords, out_shape)
    in_cat = np.concatenate([r[0] for r in rules if r[0].size])
    out_cat = np.concatenate([r[1] for r in rules if r[0].size])

    def fn(v):
        return jax.ops.segment_max(v[in_cat], jnp.asarray(out_cat),
                                   num_segments=n_out)

    out_vals = dispatch.apply("sparse_max_pool3d", fn, (vals,))
    return _wrap_out(out_vals, out_coords, out_shape)


from ..nn.layer_base import Layer


class Conv3D(Layer):
    """paddle.sparse.nn.Conv3D parity (NDHWC, dilation/groups = 1)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        kd, kh, kw = _triple(kernel_size)
        self.weight = self.create_parameter(
            [kd, kh, kw, in_channels, out_channels], attr=weight_attr)
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)
        self.stride, self.padding = stride, padding
        self.dilation, self.groups = dilation, groups
        self._subm = False

    def forward(self, x):
        if self._subm:
            return subm_conv3d(x, self.weight, self.bias,
                               stride=self.stride, padding=self.padding)
        return conv3d(x, self.weight, self.bias, self.stride,
                      self.padding, self.dilation, self.groups)


class SubmConv3D(Conv3D):
    """paddle.sparse.nn.SubmConv3D parity (stride must be 1)."""

    def __init__(self, *args, **kw):
        kw.pop("key", None)
        super().__init__(*args, **kw)
        if _triple(self.stride) != (1, 1, 1):
            raise ValueError("SubmConv3D requires stride 1")
        self._subm = True


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return max_pool3d(x, self.kernel_size, self.stride, self.padding)


class BatchNorm(Layer):
    """paddle.sparse.nn.BatchNorm parity: 1-D BN over the nnz values
    (channel-last), pattern unchanged."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from ..nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        vals, coords, shape = _sparse_values(x)
        out_vals = self._bn(vals)
        return _wrap_out(out_vals, coords, shape)
