"""GradScaler — dynamic loss scaling.

Parity: `python/paddle/amp/grad_scaler.py` →
`python/paddle/fluid/dygraph/amp/loss_scaler.py:293` (`AmpScaler`), built on
the `check_finite_and_unscale` / `update_loss_scaling` kernels
(`paddle/fluid/operators/amp/`). With bf16 (TPU default) scaling is not
needed; the class honours `enable=False` transparently and implements the
full dynamic-scale state machine for fp16 parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .. import ops


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        return ops.scale(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._params_with_grad()
        self._found_inf = False
        inv = 1.0 / self._scale
        for p in params:
            g = p.grad._data.astype(jnp.float32) * inv
            if not bool(jnp.isfinite(g).all()):
                self._found_inf = True
            p.grad._data = g.astype(p.grad._data.dtype)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(np.float32(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)


AmpScaler = GradScaler
