from .gpt import (  # noqa: F401
    GPTModel, GPTForPretraining, GPTPretrainingCriterion, GPTDecoderLayer,
    gpt_tiny, gpt2_small, gpt2_medium, gpt3_1p3b,
)
