"""GPT model family (paddle-API, nn.Layer-based).

Parity target: the PaddleNLP/fleetx GPT used in the reference's hybrid
parallel examples (BASELINE config 4). For the performance/parallel path
use `paddle_tpu.parallel.hybrid_gpt.HybridGPT` — this class is the
user-facing eager/single-chip model.
"""
from __future__ import annotations

import math

from .. import nn
from .. import ops
from ..core.tensor import Tensor


class GPTDecoderLayer(nn.Layer):
    def __init__(self, d_model, n_heads, d_ff, dropout=0.0):
        super().__init__()
        self.ln1 = nn.LayerNorm(d_model)
        self.attn = nn.MultiHeadAttention(d_model, n_heads, dropout=dropout)
        self.ln2 = nn.LayerNorm(d_model)
        self.fc1 = nn.Linear(d_model, d_ff)
        self.fc2 = nn.Linear(d_ff, d_model)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        h = self.ln1(x)
        x = x + self.dropout(self.attn(h, h, h, attn_mask=mask))
        h = self.ln2(x)
        x = x + self.dropout(self.fc2(nn.functional.gelu(self.fc1(h))))
        return x


class GPTModel(nn.Layer):
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, hidden_dropout_prob=0.0):
        super().__init__()
        d_ff = intermediate_size or 4 * hidden_size
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings,
                                                hidden_size)
        self.layers = nn.LayerList([
            GPTDecoderLayer(hidden_size, num_attention_heads, d_ff,
                            hidden_dropout_prob)
            for _ in range(num_layers)])
        self.ln_f = nn.LayerNorm(hidden_size)

    def forward(self, input_ids, position_ids=None):
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(seq, dtype="int64")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        # causal mask: bool [S, S], True = attend
        mask = ops.cast(ops.tril(ops.ones([seq, seq], "float32")), "bool")
        for layer in self.layers:
            x = layer(x, mask=mask)
        return self.ln_f(x)


class GPTForPretraining(nn.Layer):
    def __init__(self, gpt: GPTModel):
        super().__init__()
        self.gpt = gpt
        self.lm_head = nn.Linear(gpt.hidden_size, gpt.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        return self.lm_head(hidden)


class GPTPretrainingCriterion(nn.Layer):
    def forward(self, prediction_scores, masked_lm_labels,
                loss_mask=None):
        per_tok = nn.functional.cross_entropy(
            prediction_scores.reshape([-1, prediction_scores.shape[-1]]),
            masked_lm_labels.reshape([-1]), reduction="none")
        if loss_mask is not None:
            mask = loss_mask.reshape([-1]).astype("float32")
            from .. import ops
            return ops.sum(per_tok * mask) / ops.maximum(
                ops.sum(mask), ops.to_tensor(1e-8))
        from .. import ops
        return ops.mean(per_tok)


def gpt_tiny(**kw):
    return GPTModel(vocab_size=1024, hidden_size=128, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=256,
                    **kw)


def gpt2_small(**kw):
    return GPTModel(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_attention_heads=12, **kw)


def gpt2_medium(**kw):
    return GPTModel(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, **kw)


def gpt3_1p3b(**kw):
    return GPTModel(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_attention_heads=16, max_position_embeddings=2048,
                    **kw)
