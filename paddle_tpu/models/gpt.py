"""GPT model family (paddle-API, nn.Layer-based).

Parity target: the PaddleNLP/fleetx GPT used in the reference's hybrid
parallel examples (BASELINE config 4). For the performance/parallel path
use `paddle_tpu.parallel.hybrid_gpt.HybridGPT` — this class is the
user-facing eager/single-chip model.
"""
from __future__ import annotations

import math

from .. import nn
from .. import ops
from ..core.tensor import Tensor


class GPTDecoderLayer(nn.Layer):
    def __init__(self, d_model, n_heads, d_ff, dropout=0.0):
        super().__init__()
        self.ln1 = nn.LayerNorm(d_model)
        self.attn = nn.MultiHeadAttention(d_model, n_heads, dropout=dropout)
        self.ln2 = nn.LayerNorm(d_model)
        self.fc1 = nn.Linear(d_model, d_ff)
        self.fc2 = nn.Linear(d_ff, d_model)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        h = self.ln1(x)
        x = x + self.dropout(self.attn(h, h, h, attn_mask=mask))
        h = self.ln2(x)
        x = x + self.dropout(self.fc2(nn.functional.gelu(self.fc1(h))))
        return x


class GPTModel(nn.Layer):
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, hidden_dropout_prob=0.0):
        super().__init__()
        d_ff = intermediate_size or 4 * hidden_size
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings,
                                                hidden_size)
        self.layers = nn.LayerList([
            GPTDecoderLayer(hidden_size, num_attention_heads, d_ff,
                            hidden_dropout_prob)
            for _ in range(num_layers)])
        self.ln_f = nn.LayerNorm(hidden_size)

    def forward(self, input_ids, position_ids=None):
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(seq, dtype="int64")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        # causal mask: bool [S, S], True = attend
        mask = ops.cast(ops.tril(ops.ones([seq, seq], "float32")), "bool")
        for layer in self.layers:
            x = layer(x, mask=mask)
        return self.ln_f(x)


class GPTForPretraining(nn.Layer):
    def __init__(self, gpt: GPTModel):
        super().__init__()
        self.gpt = gpt
        self.lm_head = nn.Linear(gpt.hidden_size, gpt.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        return self.lm_head(hidden)


class GPTPretrainingCriterion(nn.Layer):
    def forward(self, prediction_scores, masked_lm_labels,
                loss_mask=None):
        per_tok = nn.functional.cross_entropy(
            prediction_scores.reshape([-1, prediction_scores.shape[-1]]),
            masked_lm_labels.reshape([-1]), reduction="none")
        if loss_mask is not None:
            mask = loss_mask.reshape([-1]).astype("float32")
            from .. import ops
            return ops.sum(per_tok * mask) / ops.maximum(
                ops.sum(mask), ops.to_tensor(1e-8))
        from .. import ops
        return ops.mean(per_tok)


def gpt_tiny(**kw):
    return GPTModel(vocab_size=1024, hidden_size=128, num_layers=2,
                    num_attention_heads=4, max_position_embeddings=256,
                    **kw)


def gpt2_small(**kw):
    return GPTModel(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_attention_heads=12, **kw)


def gpt2_medium(**kw):
    return GPTModel(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, **kw)


def gpt3_1p3b(**kw):
    return GPTModel(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_attention_heads=16, max_position_embeddings=2048,
                    **kw)


from ..incubate.nn.generation import GenerationMixin  # noqa: E402


class GPTForGeneration(nn.Layer, GenerationMixin):
    """Serving-side GPT: `FusedMultiTransformer` decode stack +
    `generate()` — the capability behind the reference's
    `fused_multi_transformer_op.cu` serving path (see
    `incubate/nn/fused_transformer.py`).

    `weight_only=True` swaps the matmul weights to int8 + scales
    (`weight_only_linear_kernel.h` parity); `moe=dict(num_expert=..,
    top_k=..)` builds the `FusedMultiTransformerMoe` stack (weight-only
    MoE when both are given).
    """

    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, weight_only=False,
                 moe=None, compute_dtype="float32"):
        super().__init__()
        from ..incubate.nn.fused_transformer import (
            FusedMultiTransformer, FusedMultiTransformerMoe,
            FusedMultiTransformerMoeWeightOnly,
            FusedMultiTransformerWeightOnly)
        d_ff = intermediate_size or 4 * hidden_size
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.max_position_embeddings = max_position_embeddings
        self._compute_dtype = compute_dtype
        self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings,
                                                hidden_size)
        if moe and weight_only:
            self.decoder = FusedMultiTransformerMoeWeightOnly(
                hidden_size, num_attention_heads, d_ff,
                normalize_before=True, activation="gelu",
                num_layers=num_layers, **moe)
        elif moe:
            self.decoder = FusedMultiTransformerMoe(
                hidden_size, num_attention_heads, d_ff,
                normalize_before=True, activation="gelu",
                num_layers=num_layers, **moe)
        else:
            self.decoder = FusedMultiTransformer(
                hidden_size, num_attention_heads, d_ff,
                normalize_before=True, activation="gelu",
                num_layers=num_layers)
            if weight_only:
                self.decoder = FusedMultiTransformerWeightOnly.from_float(
                    self.decoder)
        self.ln_f = nn.LayerNorm(hidden_size)
        self.lm_head = nn.Linear(hidden_size, vocab_size,
                                 bias_attr=False)

    # ---- eager scoring path (parity oracle) -----------------------------
    def forward(self, input_ids):
        seq = input_ids.shape[1]
        position_ids = ops.arange(seq, dtype="int64")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        hidden = self.decoder(x)
        return self.lm_head(self.ln_f(hidden))

    # ---- GenerationMixin cores ------------------------------------------
    def _gen_tensors(self):
        names, dec_tensors = self.decoder._param_tensors()
        self._dec_names = names
        return ([self.word_embeddings.weight,
                 self.position_embeddings.weight] + dec_tensors +
                [self.ln_f.weight, self.ln_f.bias, self.lm_head.weight])

    def _gen_cache(self, batch, s_max, dtype):
        import jax.numpy as jnp
        d = self.decoder
        L, H, Dh = d.num_layers, d.num_heads, d.head_dim
        return (jnp.zeros((L, batch, H, Dh, s_max), jnp.dtype(dtype)),
                jnp.zeros((L, batch, H, s_max, Dh), jnp.dtype(dtype)))

    def _split_arrays(self, arrays):
        n_dec = len(self._dec_names)
        return (arrays[0], arrays[1], arrays[2:2 + n_dec],
                arrays[-3], arrays[-2], arrays[-1])

    def _embed(self, we, pe, ids, positions):
        import jax.numpy as jnp
        positions = jnp.clip(positions, 0,
                             self.max_position_embeddings - 1)
        x = we[ids] + pe[positions]
        return x.astype(jnp.dtype(self._compute_dtype))

    def _prefill_core(self, arrays, ids, seq_lens, cache):
        import jax.numpy as jnp
        from ..incubate.nn.fused_transformer import _run_stack, _ln
        we, pe, dec, lnw, lnb, head = self._split_arrays(arrays)
        S = ids.shape[1]
        x = self._embed(we, pe, ids, jnp.arange(S)[None, :])
        params = dict(zip(self._dec_names, dec))
        cfg = self.decoder._cfg()
        out, cache, _ = _run_stack(cfg, params, x, cache, "prefill",
                                   None, seq_lens, None, None, False)
        out = _ln(out, lnw, lnb, 1e-5)
        idx = (seq_lens - 1)[:, None, None]
        h_last = jnp.take_along_axis(
            out, jnp.broadcast_to(idx, (out.shape[0], 1, out.shape[2])),
            axis=1)[:, 0]
        logits = jnp.matmul(h_last, head.astype(h_last.dtype))
        return logits, cache

    def _decode_core(self, arrays, token, positions, cache):
        import jax.numpy as jnp
        from ..incubate.nn.fused_transformer import _run_stack, _ln
        we, pe, dec, lnw, lnb, head = self._split_arrays(arrays)
        pos_col = positions[None, None] if positions.ndim == 0 \
            else positions[:, None]
        x = self._embed(we, pe, token[:, None], pos_col)
        params = dict(zip(self._dec_names, dec))
        cfg = self.decoder._cfg()
        out, cache, _ = _run_stack(cfg, params, x, cache, "decode",
                                   positions, None, None, None, False)
        out = _ln(out[:, 0], lnw, lnb, 1e-5)
        logits = jnp.matmul(out, head.astype(out.dtype))
        return logits, cache

    def _verify_core(self, arrays, tokens, positions, cache):
        """Speculative verify: score K consecutive tokens in one pass.

        tokens [B, K] int32, positions [B] (or scalar) — the position of
        tokens[:, 0]; token j lands at positions + j. Returns
        (logits [B, K, V], new_cache): logits[:, j] scores the
        next-token distribution AFTER token j, so a greedy argmax over
        axis -1 yields the sequential-greedy continuation for every
        accepted prefix (see incubate/nn/generation.py)."""
        import jax.numpy as jnp
        from ..incubate.nn.fused_transformer import _run_stack, _ln
        we, pe, dec, lnw, lnb, head = self._split_arrays(arrays)
        K = tokens.shape[1]
        offs = jnp.arange(K, dtype=jnp.int32)
        pos = (positions + offs)[None, :] if positions.ndim == 0 \
            else positions[:, None] + offs[None, :]
        x = self._embed(we, pe, tokens, pos)
        params = dict(zip(self._dec_names, dec))
        cfg = self.decoder._cfg()
        out, cache, _ = _run_stack(cfg, params, x, cache, "decode",
                                   positions, None, None, None, False)
        out = _ln(out, lnw, lnb, 1e-5)                    # [B, K, D]
        logits = jnp.matmul(out, head.astype(out.dtype))
        return logits, cache

    @classmethod
    def from_pretraining(cls, model: "GPTForPretraining",
                         compute_dtype="float32", weight_only=False):
        """Repack an eager `GPTForPretraining` into the fused serving
        layout (per-layer q/k/v/out params -> stacked [L, ...])."""
        import numpy as np
        gpt = model.gpt
        L = len(gpt.layers)
        H = gpt.layers[0].attn.num_heads
        d = gpt.hidden_size
        d_ff = gpt.layers[0].fc1._out_features
        new = cls(vocab_size=gpt.vocab_size, hidden_size=d, num_layers=L,
                  num_attention_heads=H, intermediate_size=d_ff,
                  max_position_embeddings=gpt.position_embeddings
                  ._num_embeddings, compute_dtype=compute_dtype)
        new.word_embeddings.weight.set_value(gpt.word_embeddings.weight)
        new.position_embeddings.weight.set_value(
            gpt.position_embeddings.weight)
        dec = new.decoder

        def stack(get):
            return np.stack([np.asarray(get(l).numpy())
                             for l in gpt.layers])
        dec.ln_scales.set_value(stack(lambda l: l.ln1.weight))
        dec.ln_biases.set_value(stack(lambda l: l.ln1.bias))
        dec.qkv_weights.set_value(np.concatenate(
            [stack(lambda l: l.attn.q_proj.weight),
             stack(lambda l: l.attn.k_proj.weight),
             stack(lambda l: l.attn.v_proj.weight)], axis=2))
        dec.qkv_biases.set_value(np.concatenate(
            [stack(lambda l: l.attn.q_proj.bias),
             stack(lambda l: l.attn.k_proj.bias),
             stack(lambda l: l.attn.v_proj.bias)], axis=1))
        dec.linear_weights.set_value(
            stack(lambda l: l.attn.out_proj.weight))
        dec.linear_biases.set_value(stack(lambda l: l.attn.out_proj.bias))
        dec.ffn_ln_scales.set_value(stack(lambda l: l.ln2.weight))
        dec.ffn_ln_biases.set_value(stack(lambda l: l.ln2.bias))
        dec.ffn1_weights.set_value(stack(lambda l: l.fc1.weight))
        dec.ffn1_biases.set_value(stack(lambda l: l.fc1.bias))
        dec.ffn2_weights.set_value(stack(lambda l: l.fc2.weight))
        dec.ffn2_biases.set_value(stack(lambda l: l.fc2.bias))
        new.ln_f.weight.set_value(gpt.ln_f.weight)
        new.ln_f.bias.set_value(gpt.ln_f.bias)
        new.lm_head.weight.set_value(model.lm_head.weight)
        if weight_only:
            from ..incubate.nn.fused_transformer import (
                FusedMultiTransformerWeightOnly)
            new.decoder = FusedMultiTransformerWeightOnly.from_float(
                new.decoder)
        return new
