"""BERT model family (BASELINE config 3: BERT-base pretrain with fused
attention + LAMB).

Parity target: PaddleNLP's BertModel / BertForPretraining as exercised by
the reference's `fused_attention_op.cu` path — here the encoder rides
`nn.TransformerEncoder` whose attention goes through
`F.scaled_dot_product_attention` (XLA-fused / Pallas).
"""
from __future__ import annotations

from .. import nn
from .. import ops
from ..core.tensor import Tensor


class BertEmbeddings(nn.Layer):
    def __init__(self, vocab_size, hidden_size, max_position_embeddings,
                 type_vocab_size, hidden_dropout_prob=0.1):
        super().__init__()
        self.word_embeddings = nn.Embedding(vocab_size, hidden_size)
        self.position_embeddings = nn.Embedding(max_position_embeddings,
                                                hidden_size)
        self.token_type_embeddings = nn.Embedding(type_vocab_size,
                                                  hidden_size)
        self.layer_norm = nn.LayerNorm(hidden_size)
        self.dropout = nn.Dropout(hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(seq, dtype="int64")
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        emb = self.word_embeddings(input_ids) \
            + self.position_embeddings(position_ids) \
            + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = nn.Linear(hidden_size, hidden_size)
        self.activation = nn.Tanh()

    def forward(self, hidden_states):
        return self.activation(self.dense(hidden_states[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        super().__init__()
        self.pad_token_id = pad_token_id
        self.embeddings = BertEmbeddings(
            vocab_size, hidden_size, max_position_embeddings,
            type_vocab_size, hidden_dropout_prob)
        encoder_layer = nn.TransformerEncoderLayer(
            hidden_size, num_attention_heads, intermediate_size,
            dropout=hidden_dropout_prob, activation=hidden_act,
            attn_dropout=attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(encoder_layer,
                                             num_hidden_layers)
        self.pooler = BertPooler(hidden_size)
        self.hidden_size = hidden_size
        self.vocab_size = vocab_size
        self.num_layers = num_hidden_layers

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is None:
            attention_mask = ops.not_equal(
                input_ids, ops.full_like(input_ids, self.pad_token_id))
        # [B, S] -> bool key-padding mask [B, 1, 1, S]: stays bool so
        # scaled_dot_product_attention can fold it into the splash flash
        # kernel as segment ids when attention dropout is 0 (eval,
        # long-sequence pretrain configs). With probs dropout active the
        # additive XLA path runs either way — the r5 BERT bench win came
        # from AMP O2 + the rbg dropout RNG (core/random.py), not this.
        mask = ops.unsqueeze(ops.cast(attention_mask, "bool"), [1, 2])
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq_out = self.encoder(emb, src_mask=mask)
        pooled = self.pooler(seq_out)
        return seq_out, pooled


class BertPretrainingHeads(nn.Layer):
    def __init__(self, hidden_size, vocab_size, activation="gelu",
                 embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(hidden_size, hidden_size)
        self.activation = nn.GELU()
        self.layer_norm = nn.LayerNorm(hidden_size)
        # weight tying (reference: decoder_weight = embedding table)
        self._tied_weight = embedding_weights
        if embedding_weights is None:
            self.decoder = nn.Linear(hidden_size, vocab_size)
        else:
            self.decoder = None
            self.decoder_bias = self.create_parameter(
                [vocab_size], is_bias=True)
        self.seq_relationship = nn.Linear(hidden_size, 2)

    def forward(self, sequence_output, pooled_output):
        h = self.layer_norm(self.activation(self.transform(
            sequence_output)))
        if self.decoder is not None:
            prediction_scores = self.decoder(h)
        else:
            from .. import ops
            prediction_scores = ops.matmul(
                h, self._tied_weight, transpose_y=True) + self.decoder_bias
        seq_relationship_score = self.seq_relationship(pooled_output)
        return prediction_scores, seq_relationship_score


class BertForPretraining(nn.Layer):
    def __init__(self, bert: BertModel):
        super().__init__()
        self.bert = bert
        # reference ties the MLM decoder to the word embedding table
        self.cls = BertPretrainingHeads(
            bert.hidden_size, bert.vocab_size,
            embedding_weights=bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids,
                                    position_ids, attention_mask)
        return self.cls(seq_out, pooled)


class BertPretrainingCriterion(nn.Layer):
    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels=None,
                masked_lm_scale=1.0):
        mlm = nn.functional.cross_entropy(
            prediction_scores.reshape([-1, self.vocab_size]),
            masked_lm_labels.reshape([-1]), ignore_index=-1,
            reduction="mean")
        if next_sentence_labels is None:
            return mlm
        nsp = nn.functional.cross_entropy(
            seq_relationship_score, next_sentence_labels.reshape([-1]),
            reduction="mean")
        return mlm + nsp


class BertForSequenceClassification(nn.Layer):
    def __init__(self, bert: BertModel, num_classes=2, dropout=None):
        super().__init__()
        self.bert = bert
        self.dropout = nn.Dropout(dropout if dropout is not None else 0.1)
        self.classifier = nn.Linear(bert.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))


def bert_tiny(**kw):
    return BertModel(vocab_size=1024, hidden_size=128,
                     num_hidden_layers=2, num_attention_heads=4,
                     intermediate_size=256, max_position_embeddings=128,
                     **kw)


def bert_base(**kw):
    return BertModel(**kw)


def bert_large(**kw):
    return BertModel(hidden_size=1024, num_hidden_layers=24,
                     num_attention_heads=16, intermediate_size=4096, **kw)
