"""jaxpr -> ONNX graph converter + numpy ONNX interpreter.

Parity: `python/paddle/onnx/export.py` (paddle2onnx) — the deliverable
is an actual .onnx protobuf artifact. TPU-native re-design: instead of
walking a static Program, the model's forward is traced to a jaxpr
(params captured as constants -> initializers) and each primitive maps
to an ONNX op. The interpreter (`run_model`) executes a decoded model
in numpy so tests verify exported artifacts end-to-end without the
`onnx`/`onnxruntime` packages (absent in this environment).

Supported primitive set: the nn layer library's inference graphs —
matmul/dot_general, conv (NCHW, groups), elementwise arithmetic,
(log)softmax-style reductions, max/avg pooling via reduce_window,
transpose/reshape/broadcast/concat/slice/squeeze, tanh/erf/exp/log/
rsqrt/logistic, select_n, convert_element_type. Anything else raises
UnsupportedOnnxExport with the primitive name.
"""
from __future__ import annotations

import numpy as np

from . import onnx_format as F


class UnsupportedOnnxExport(NotImplementedError):
    pass


def _np(x):
    return np.asarray(x)


class _Converter:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.names = {}          # jax var -> onnx name
        self.counter = 0

    def name_of(self, var):
        if var not in self.names:
            self.counter += 1
            self.names[var] = f"t{self.counter}"
        return self.names[var]

    def fresh(self, prefix="tmp"):
        self.counter += 1
        return f"{prefix}{self.counter}"

    def add_const(self, arr, name=None):
        name = name or self.fresh("const")
        self.initializers.append(F.tensor(name, _np(arr)))
        return name

    def add_node(self, op, inputs, outputs=None, attrs=None):
        out = outputs or [self.fresh(op.lower())]
        self.nodes.append(F.node(op, inputs, out, attrs=attrs or {}))
        return out[0]

    # ---- primitive handlers ------------------------------------------
    def convert_eqn(self, eqn, inp):
        """inp: list of onnx names (or np constants) for eqn.invars."""
        p = eqn.primitive.name
        out_var = eqn.outvars[0]
        out = self.name_of(out_var)
        a = inp

        def n(op, ins, attrs=None):
            self.add_node(op, ins, [out], attrs)

        binops = {"add": "Add", "sub": "Sub", "mul": "Mul",
                  "div": "Div", "max": "Max", "min": "Min",
                  "pow": "Pow"}
        unops = {"tanh": "Tanh", "exp": "Exp", "log": "Log",
                 "logistic": "Sigmoid", "erf": "Erf", "neg": "Neg",
                 "abs": "Abs", "sqrt": "Sqrt", "floor": "Floor",
                 "ceil": "Ceil", "sign": "Sign", "sin": "Sin",
                 "cos": "Cos", "stop_gradient": "Identity",
                 "copy": "Identity"}
        if p in binops:
            n(binops[p], a)
        elif p == "rem":
            # jax rem = C fmod (sign of dividend); ONNX Mod defaults to
            # divisor-sign semantics and is spec-invalid on floats
            n("Mod", a, {"fmod": 1})
        elif p in unops:
            n(unops[p], [a[0]])
        elif p == "erfc":
            e = self.add_node("Erf", [a[0]])
            one = self.add_const(np.ones((), _np_dtype(eqn.invars[0])))
            n("Sub", [one, e])
        elif p == "rsqrt":
            s = self.add_node("Sqrt", [a[0]])
            one = self.add_const(np.ones((), _np_dtype(eqn.invars[0])))
            n("Div", [one, s])
        elif p == "integer_pow":
            y = eqn.params["y"]
            e = self.add_const(
                np.asarray(y, _np_dtype(eqn.invars[0])))
            n("Pow", [a[0], e])
        elif p == "dot_general":
            self._dot_general(eqn, a, out)
        elif p == "conv_general_dilated":
            self._conv(eqn, a, out)
        elif p == "reduce_window_max":
            self._pool(eqn, a, out, "MaxPool")
        elif p == "reduce_window_sum":
            self._pool(eqn, a, out, "_SumPool")
        elif p in ("reduce_sum", "reduce_max", "reduce_min",
                   "reduce_prod"):
            op = {"reduce_sum": "ReduceSum", "reduce_max": "ReduceMax",
                  "reduce_min": "ReduceMin",
                  "reduce_prod": "ReduceProd"}[p]
            axes = [int(x) for x in eqn.params["axes"]]
            if op == "ReduceSum":
                # opset 13 moved ReduceSum's axes to a second INPUT
                ax = self.add_const(np.asarray(axes, np.int64))
                n(op, [a[0], ax], {"keepdims": 0})
            else:
                n(op, [a[0]], {"axes": axes, "keepdims": 0})
        elif p == "reduce_and":
            axes = [int(x) for x in eqn.params["axes"]]
            f32 = self.add_node("Cast", [a[0]], attrs={"to": F.FLOAT})
            red = self.add_node("ReduceMin", [f32],
                                attrs={"axes": axes, "keepdims": 0})
            n("Cast", [red], {"to": F.BOOL})
        elif p == "transpose":
            n("Transpose", [a[0]],
              {"perm": [int(x) for x in eqn.params["permutation"]]})
        elif p == "reshape":
            sizes = [int(s) for s in eqn.params["new_sizes"]]
            in_shape = eqn.invars[0].aval.shape
            if sizes and in_shape and sizes[0] == in_shape[0]:
                # leading (batch) dim preserved -> export as dynamic so
                # flatten-style reshapes work at any batch size
                sizes = [-1] + sizes[1:]
            shp = self.add_const(np.asarray(sizes, np.int64))
            n("Reshape", [a[0], shp])
        elif p == "squeeze":
            axes = [int(x) for x in eqn.params["dimensions"]]
            shp = self.add_const(
                np.asarray(eqn.outvars[0].aval.shape, np.int64))
            n("Reshape", [a[0], shp])
        elif p == "broadcast_in_dim":
            self._broadcast(eqn, a, out)
        elif p == "concatenate":
            n("Concat", a, {"axis": int(eqn.params["dimension"])})
        elif p == "slice":
            starts = [int(x) for x in eqn.params["start_indices"]]
            ends = [int(x) for x in eqn.params["limit_indices"]]
            axes = list(range(len(starts)))
            strides = eqn.params.get("strides")
            attrs = [self.add_const(np.asarray(v, np.int64))
                     for v in (starts, ends, axes,
                               strides or [1] * len(starts))]
            n("Slice", [a[0]] + attrs)
        elif p == "rev":
            dims = [int(x) for x in eqn.params["dimensions"]]
            shape = eqn.invars[0].aval.shape
            starts = self.add_const(np.asarray(
                [shape[d] - 1 for d in dims], np.int64))
            ends = self.add_const(np.asarray(
                [-(shape[d] + 1) for d in dims], np.int64))
            axes_c = self.add_const(np.asarray(dims, np.int64))
            steps = self.add_const(np.asarray([-1] * len(dims), np.int64))
            n("Slice", [a[0], starts, ends, axes_c, steps])
        elif p == "select_n":
            # select_n(pred, on_false, on_true) with bool pred
            n("Where", [a[0], a[2], a[1]])
        elif p == "convert_element_type":
            to = F._NP2ONNX[np.dtype(eqn.params["new_dtype"])]
            n("Cast", [a[0]], {"to": int(to)})
        elif p in ("eq", "ne", "lt", "le", "gt", "ge"):
            op = {"eq": "Equal", "lt": "Less", "le": "LessOrEqual",
                  "gt": "Greater", "ge": "GreaterOrEqual"}.get(p)
            if p == "ne":
                e = self.add_node("Equal", a)
                n("Not", [e])
            else:
                n(op, a)
        elif p == "and":
            n("And", a)
        elif p == "or":
            n("Or", a)
        elif p == "not":
            n("Not", [a[0]])
        elif p == "iota":
            dt = _np_dtype(eqn.outvars[0])
            arr = np.arange(eqn.outvars[0].aval.shape[
                eqn.params["dimension"]], dtype=dt)
            arr = np.broadcast_to(
                arr.reshape([-1 if i == eqn.params["dimension"] else 1
                             for i in range(
                                 len(eqn.outvars[0].aval.shape))]),
                eqn.outvars[0].aval.shape)
            cname = self.add_const(np.ascontiguousarray(arr))
            n("Identity", [cname])
        elif p in ("custom_jvp_call", "custom_vjp_call", "pjit", "jit",
                   "closed_call", "core_call", "remat"):
            self._subjaxpr(eqn, a)
        else:
            raise UnsupportedOnnxExport(
                f"primitive '{p}' has no ONNX mapping")

    def _subjaxpr(self, eqn, inp):
        sub = eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
        if sub is None:
            raise UnsupportedOnnxExport(eqn.primitive.name)
        closed = sub if hasattr(sub, "jaxpr") else None
        jaxpr = closed.jaxpr if closed is not None else sub
        consts = closed.consts if closed is not None else []
        if eqn.primitive.name == "custom_jvp_call":
            # invars beyond the jaxpr's inputs are tangent plumbing
            inp = inp[:len(jaxpr.invars) - len(jaxpr.constvars)]
        self._walk(jaxpr, consts, inp, eqn.outvars)

    def _walk(self, jaxpr, consts, in_names, final_outvars=None):
        for cv, cval in zip(jaxpr.constvars, consts):
            self.names[cv] = self.add_const(_np(cval))
        for v, name in zip(jaxpr.invars, in_names):
            self.names[v] = name
        for eqn in jaxpr.eqns:
            inp = []
            for iv in eqn.invars:
                if hasattr(iv, "val"):  # Literal
                    inp.append(self.add_const(_np(iv.val)))
                else:
                    inp.append(self.name_of(iv))
            self.convert_eqn(eqn, inp)
        outs = []
        for ov in jaxpr.outvars:
            if hasattr(ov, "val"):
                outs.append(self.add_const(_np(ov.val)))
            else:
                outs.append(self.name_of(ov))
        if final_outvars is not None:
            for fv, name in zip(final_outvars, outs):
                self.names[fv] = name
        return outs

    def _dot_general(self, eqn, a, out):
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        lshape = eqn.invars[0].aval.shape
        rshape = eqn.invars[1].aval.shape
        # plain matmul: contract last of lhs with second-to-last (2D) or
        # first (2D rhs) of rhs, no batch dims
        if not lb and not rb and len(lc) == 1 and len(rc) == 1 and \
                lc[0] == len(lshape) - 1 and \
                rc[0] == max(len(rshape) - 2, 0):
            self.add_node("MatMul", a, [out])
            return
        raise UnsupportedOnnxExport(
            f"dot_general dims {eqn.params['dimension_numbers']}")

    def _conv(self, eqn, a, out):
        dn = eqn.params["dimension_numbers"]
        # normalize arbitrary operand layouts (our conv uses channels-
        # last internally) to ONNX's NCHW/OIHW via Transpose nodes
        lhs_perm = (dn.lhs_spec[0], dn.lhs_spec[1]) + \
            tuple(dn.lhs_spec[2:])
        rhs_perm = (dn.rhs_spec[0], dn.rhs_spec[1]) + \
            tuple(dn.rhs_spec[2:])
        x_in, w_in = a[0], a[1]
        if lhs_perm != tuple(range(len(lhs_perm))):
            x_in = self.add_node("Transpose", [x_in],
                                 attrs={"perm": list(lhs_perm)})
        if rhs_perm != tuple(range(len(rhs_perm))):
            w_in = self.add_node("Transpose", [w_in],
                                 attrs={"perm": list(rhs_perm)})
        strides = [int(s) for s in eqn.params["window_strides"]]
        pads = eqn.params["padding"]
        dil = [int(d) for d in eqn.params["rhs_dilation"]]
        groups = int(eqn.params["feature_group_count"])
        onnx_pads = [int(p[0]) for p in pads] + [int(p[1]) for p in pads]
        attrs = {"strides": strides, "pads": onnx_pads,
                 "dilations": dil, "group": groups}
        out_spec = dn.out_spec
        canon_out = (out_spec[0], out_spec[1]) + tuple(out_spec[2:])
        if canon_out == tuple(range(len(canon_out))):
            self.add_node("Conv", [x_in, w_in], [out], attrs)
            return
        y = self.add_node("Conv", [x_in, w_in], attrs=attrs)
        # NCHW -> the jaxpr's expected output layout: expected dim
        # out_spec[k] holds NCHW dim k, so transpose axes[out_spec[k]]=k
        perm = [0] * len(canon_out)
        perm[out_spec[0]] = 0
        perm[out_spec[1]] = 1
        for i, s in enumerate(out_spec[2:]):
            perm[s] = 2 + i
        self.add_node("Transpose", [y], [out], {"perm": perm})

    def _pool(self, eqn, a, out, kind):
        dims = [int(d) for d in eqn.params["window_dimensions"]]
        strides = [int(s) for s in eqn.params["window_strides"]]
        pads = [tuple(map(int, p)) for p in eqn.params["padding"]]
        rank = len(dims)
        if rank != 4:
            raise UnsupportedOnnxExport(f"pooling rank {rank}")
        if dims[0] != 1:
            raise UnsupportedOnnxExport("pooling over batch")
        nhwc = dims[1] != 1 and dims[3] == 1  # window on dims 1,2
        if nhwc:
            perm, inv = [0, 3, 1, 2], [0, 2, 3, 1]
            sp = (1, 2)
        else:
            if dims[1] != 1:
                raise UnsupportedOnnxExport("pooling over channel")
            perm = inv = None
            sp = (2, 3)
        x_in = a[0]
        if perm:
            x_in = self.add_node("Transpose", [x_in],
                                 attrs={"perm": perm})
        kshape = [dims[i] for i in sp]
        attrs = {"kernel_shape": kshape,
                 "strides": [strides[i] for i in sp],
                 "pads": [pads[i][0] for i in sp] +
                         [pads[i][1] for i in sp]}
        target = [out] if not perm else None
        if kind == "MaxPool":
            y = self.add_node("MaxPool", [x_in], target, attrs)
        else:
            # reduce_window_sum = AveragePool * window_size;
            # count_include_pad matches jax's zero-including sum
            ap = self.add_node("AveragePool", [x_in],
                               attrs={**attrs, "count_include_pad": 1})
            scale = self.add_const(
                np.asarray(float(np.prod(kshape)),
                           _np_dtype(eqn.invars[0])))
            y = self.add_node("Mul", [ap, scale], target)
        if perm:
            self.add_node("Transpose", [y], [out], {"perm": inv})

    def _broadcast(self, eqn, a, out):
        bdims = eqn.params["broadcast_dimensions"]
        tgt = eqn.outvars[0].aval.shape
        in_shape = eqn.invars[0].aval.shape
        # reshape to rank(target) with 1s, then Expand (ONNX Expand
        # broadcasts bidirectionally, so a traced batch-1 target still
        # follows a larger runtime batch)
        mid = [1] * len(tgt)
        for i, d in enumerate(bdims):
            mid[d] = in_shape[i]
        if bdims and bdims[0] == 0 and in_shape:
            mid[0] = -1   # preserved leading dim stays batch-dynamic
        shp = self.add_const(np.asarray(mid, np.int64))
        r = self.add_node("Reshape", [a[0], shp])
        tgt_c = self.add_const(np.asarray(tgt, np.int64))
        self.add_node("Expand", [r, tgt_c], [out])


def _np_dtype(var):
    return np.dtype(var.aval.dtype)


def export_jaxpr(closed_jaxpr, example_inputs, path, graph_name="model",
                 input_dims=None, opset=13):
    """closed_jaxpr: jax.make_jaxpr(fn)(x...) with params as consts.

    input_dims: optional per-input shape lists where None marks a
    dynamic dim (exported as a dim_param, typically the batch)."""
    if opset < 13:
        raise ValueError(
            "ONNX export emits opset-13 semantics (ReduceSum axes as "
            f"input); opset_version={opset} is not supported")
    conv = _Converter()
    jaxpr = closed_jaxpr.jaxpr
    in_names = []
    in_infos = []
    dynamic_batch = False
    for i, v in enumerate(jaxpr.invars):
        name = f"input_{i}"
        conv.names[v] = name
        in_names.append(name)
        shape = list(v.aval.shape)
        if input_dims is not None and i < len(input_dims):
            spec_shape = input_dims[i]
            shape = [("N" if s is None or s == -1 else int(s))
                     for s in spec_shape]
            dynamic_batch = dynamic_batch or "N" in shape
        in_infos.append(F.value_info(
            name, F._NP2ONNX[np.dtype(v.aval.dtype)], shape))
    outs = conv._walk(jaxpr, closed_jaxpr.consts, in_names)
    out_infos = []
    for name, v in zip(outs, jaxpr.outvars):
        shape = list(v.aval.shape)
        if dynamic_batch and shape:
            # outputs follow the batch when inputs are batch-dynamic
            shape = ["N"] + shape[1:]
        out_infos.append(F.value_info(
            name, F._NP2ONNX[np.dtype(v.aval.dtype)], shape))
    g = F.graph(conv.nodes, graph_name, conv.initializers, in_infos,
                out_infos)
    blob = F.model(g, opset=opset)
    with open(path, "wb") as f:
        f.write(blob)
    return path


# ---------------------------------------------------------- interpreter

def _run_node(n, env):
    op = n["op_type"]
    x = [env[i] for i in n["input"]]
    at = n["attrs"]

    def put(v):
        env[n["output"][0]] = v

    if op == "MatMul":
        put(x[0] @ x[1])
    elif op == "Add":
        put(x[0] + x[1])
    elif op == "Sub":
        put(x[0] - x[1])
    elif op == "Mul":
        put(x[0] * x[1])
    elif op == "Div":
        put(x[0] / x[1])
    elif op == "Max":
        put(np.maximum(x[0], x[1]))
    elif op == "Min":
        put(np.minimum(x[0], x[1]))
    elif op == "Pow":
        put(np.power(x[0], x[1]))
    elif op == "Mod":
        put(np.fmod(x[0], x[1]) if at.get("fmod") else
            np.mod(x[0], x[1]))
    elif op == "Neg":
        put(-x[0])
    elif op == "Abs":
        put(np.abs(x[0]))
    elif op == "Sqrt":
        put(np.sqrt(x[0]))
    elif op == "Exp":
        put(np.exp(x[0]))
    elif op == "Log":
        put(np.log(x[0]))
    elif op == "Tanh":
        put(np.tanh(x[0]))
    elif op == "Erf":
        from math import erf
        put(np.vectorize(erf)(x[0]).astype(x[0].dtype))
    elif op == "Sigmoid":
        put(1.0 / (1.0 + np.exp(-x[0])))
    elif op == "Sign":
        put(np.sign(x[0]))
    elif op == "Floor":
        put(np.floor(x[0]))
    elif op == "Ceil":
        put(np.ceil(x[0]))
    elif op == "Sin":
        put(np.sin(x[0]))
    elif op == "Cos":
        put(np.cos(x[0]))
    elif op == "Identity":
        put(x[0])
    elif op == "Not":
        put(~x[0])
    elif op == "And":
        put(x[0] & x[1])
    elif op == "Or":
        put(x[0] | x[1])
    elif op in ("Equal", "Less", "LessOrEqual", "Greater",
                "GreaterOrEqual"):
        f = {"Equal": np.equal, "Less": np.less,
             "LessOrEqual": np.less_equal, "Greater": np.greater,
             "GreaterOrEqual": np.greater_equal}[op]
        put(f(x[0], x[1]))
    elif op == "Where":
        put(np.where(x[0], x[1], x[2]))
    elif op == "Cast":
        put(x[0].astype(F._ONNX2NP[at["to"]]))
    elif op == "Transpose":
        put(np.transpose(x[0], at["perm"]))
    elif op == "Reshape":
        put(x[0].reshape([int(d) for d in x[1]]))
    elif op == "Expand":
        # ONNX Expand broadcasts BIDIRECTIONALLY (unlike broadcast_to)
        tgt = np.broadcast_shapes(x[0].shape,
                                  tuple(int(d) for d in x[1]))
        put(np.broadcast_to(x[0], tgt).copy())
    elif op == "Concat":
        put(np.concatenate(x, axis=at["axis"]))
    elif op == "Slice":
        starts, ends, axes, steps = (x[1], x[2], x[3],
                                     x[4] if len(x) > 4 else
                                     np.ones_like(x[1]))
        sl = [slice(None)] * x[0].ndim
        for s, e, ax, st in zip(starts, ends, axes, steps):
            sl[int(ax)] = slice(int(s), int(e), int(st))
        put(x[0][tuple(sl)])
    elif op in ("ReduceSum", "ReduceMax", "ReduceMin", "ReduceProd"):
        f = {"ReduceSum": np.sum, "ReduceMax": np.max,
             "ReduceMin": np.min, "ReduceProd": np.prod}[op]
        # opset 13: ReduceSum takes axes as a second input
        axes = (tuple(int(v) for v in x[1]) if len(x) > 1
                else tuple(at["axes"]))
        put(f(x[0], axis=axes, keepdims=bool(at.get("keepdims", 1))))
    elif op == "Conv":
        put(_conv_np(x[0], x[1], x[2] if len(x) > 2 else None, at))
    elif op in ("MaxPool", "AveragePool"):
        if op == "AveragePool" and not at.get("count_include_pad") and \
                any(at.get("pads", [0] * 4)):
            raise NotImplementedError(
                "interpreter: AveragePool count_include_pad=0 with pads")
        put(_pool_np(x[0], at, op))
    else:
        raise NotImplementedError(f"interpreter: {op}")


def _conv_np(x, w, b, at):
    strides = at.get("strides", [1, 1])
    pads = at.get("pads", [0] * 4)
    dil = at.get("dilations", [1, 1])
    groups = at.get("group", 1)
    N, C, H, W = x.shape
    O, Cg, kh, kw = w.shape
    ph0, pw0, ph1, pw1 = pads
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    kh_e = (kh - 1) * dil[0] + 1
    kw_e = (kw - 1) * dil[1] + 1
    Ho = (xp.shape[2] - kh_e) // strides[0] + 1
    Wo = (xp.shape[3] - kw_e) // strides[1] + 1
    out = np.zeros((N, O, Ho, Wo), x.dtype)
    og = O // groups
    for g in range(groups):
        xs = xp[:, g * Cg:(g + 1) * Cg]
        for o in range(og):
            oc = g * og + o
            acc = np.zeros((N, Ho, Wo), x.dtype)
            for i in range(kh):
                for j in range(kw):
                    patch = xs[:, :,
                               i * dil[0]:i * dil[0] + Ho * strides[0]:
                               strides[0],
                               j * dil[1]:j * dil[1] + Wo * strides[1]:
                               strides[1]]
                    acc += np.einsum("nchw,c->nhw", patch, w[oc, :, i, j])
            out[:, oc] = acc
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def _pool_np(x, at, op):
    kh, kw = at["kernel_shape"]
    sh, sw = at.get("strides", [kh, kw])
    pads = at.get("pads", [0] * 4)
    ph0, pw0, ph1, pw1 = pads
    fill = -np.inf if op == "MaxPool" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
                constant_values=fill)
    N, C, H, W = xp.shape
    Ho = (H - kh) // sh + 1
    Wo = (W - kw) // sw + 1
    out = np.full((N, C, Ho, Wo, kh * kw), fill, x.dtype)
    idx = 0
    for i in range(kh):
        for j in range(kw):
            out[..., idx] = xp[:, :, i:i + Ho * sh:sh, j:j + Wo * sw:sw]
            idx += 1
    return out.max(-1) if op == "MaxPool" else out.mean(-1)


def run_model(decoded, inputs):
    """Execute a decode_model() result on numpy inputs."""
    g = decoded["graph"]
    env = dict(g["initializers"])
    for name, arr in zip(g["inputs"], inputs):
        env[name] = np.asarray(arr)
    for n in g["nodes"]:
        _run_node(n, env)
    return [env[o] for o in g["outputs"]]
