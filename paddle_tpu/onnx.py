"""paddle.onnx — real ONNX artifact export.

Parity: `python/paddle/onnx/export.py` (paddle2onnx). The model's
forward is traced to a jaxpr (parameters captured as initializers) and
converted primitive-by-primitive into an ONNX GraphProto
(onnx_export.py over the hand-rolled protobuf writer in
onnx_format.py — the `onnx` package is not a dependency). Models whose
graphs use primitives outside the supported set raise
UnsupportedOnnxExport; the StableHLO path (`paddle_tpu.jit.save`)
remains the full-fidelity serving artifact.
"""
from __future__ import annotations

from .onnx_export import UnsupportedOnnxExport  # noqa: F401


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Write `<path>.onnx` (reference semantics: `path` is the stem).
    Returns the artifact path."""
    import jax
    import numpy as np

    from .core.tensor import Tensor
    from .core import autograd

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")

    examples = []
    for spec in input_spec:
        shape = [1 if (d is None or d == -1) else int(d)
                 for d in spec.shape]
        examples.append(np.zeros(shape, np.dtype(spec.dtype or
                                                 "float32")))

    was_training = getattr(layer, "training", False)
    layer.eval()
    try:
        def pure(*xs):
            with autograd.no_grad():
                out = layer(*[Tensor(x) for x in xs])
            outs = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data if isinstance(o, Tensor) else o
                         for o in outs)

        closed = jax.make_jaxpr(pure)(*examples)
    finally:
        if was_training:
            layer.train()
    from .onnx_export import export_jaxpr
    artifact = path if path.endswith(".onnx") else path + ".onnx"
    export_jaxpr(closed, examples, artifact,
                 graph_name=type(layer).__name__,
                 input_dims=[list(s.shape) for s in input_spec],
                 opset=int(opset_version))
    return artifact
