"""hapi callbacks — parity: `python/paddle/hapi/callbacks.py`
(Callback, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kw):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kw)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        out = []
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else 0.0
            if isinstance(v, (float, np.floating)):
                out.append(f"{k}: {v:.4f}")
            else:
                out.append(f"{k}: {v}")
        return " - ".join(out)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            ips = (step + 1) / max(time.time() - self._start, 1e-9)
            print(f"step {step + 1}/{self.steps or '?'} - "
                  f"{self._fmt(logs)} - {ips:.2f} step/s")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            print(f"Epoch {epoch + 1} done in {dur:.1f}s - "
                  f"{self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            value = value[0]
        if self.better(value, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class MetricsExporter(Callback):
    """Feeds the profiler metrics registry from the fit loop: epoch and
    batch counters plus a rolling steps/sec gauge (the gauge is also set
    by profiler.timer.Benchmark, which sees the grouped-dispatch step
    count; this callback covers non-fit drivers that only fire
    callbacks). Appended by `config_callbacks` when metrics are
    enabled; every hook is a no-op when they are off."""

    def __init__(self, window=20):
        super().__init__()
        self.window = window
        self._times = []

    def on_train_begin(self, logs=None):
        self._times = []

    def on_train_batch_end(self, step, logs=None):
        from ..profiler import metrics as _metrics
        if not _metrics._enabled:
            return
        _metrics.HAPI_BATCHES.labels("train").inc()
        now = time.perf_counter()
        self._times.append(now)
        if len(self._times) > self.window:
            self._times.pop(0)
        if len(self._times) >= 2:
            dt = self._times[-1] - self._times[0]
            if dt > 0:
                _metrics.STEPS_PER_SEC.set(
                    (len(self._times) - 1) / dt)

    def on_eval_batch_end(self, step, logs=None):
        from ..profiler import metrics as _metrics
        if _metrics._enabled:
            _metrics.HAPI_BATCHES.labels("eval").inc()

    def on_epoch_end(self, epoch, logs=None):
        from ..profiler import metrics as _metrics
        if _metrics._enabled:
            _metrics.HAPI_EPOCHS.inc()


class VisualDL(Callback):
    """Placeholder parity shim — logs scalars to a jsonl file."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps({"step": self._step,
                                **{k: float(np.asarray(v).reshape(-1)[0])
                                   for k, v in (logs or {}).items()
                                   if not isinstance(v, str)}}) + "\n")
        self._step += 1


def config_callbacks(callbacks=None, model=None, batch_size=None,
                     epochs=None, steps=None, log_freq=2, verbose=2,
                     save_freq=1, save_dir=None, metrics=None,
                     mode="train", do_eval=False):
    from ..profiler import metrics as _metrics
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if _metrics._enabled and not any(isinstance(c, MetricsExporter)
                                     for c in cbks):
        cbks.append(MetricsExporter())
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs,
                    "steps": steps, "verbose": verbose,
                    "metrics": metrics or [], "do_eval": bool(do_eval)})
    return lst


class ReduceLROnPlateau(Callback):
    """`hapi/callbacks.py ReduceLROnPlateau` parity: scale the LR by
    `factor` when `monitor` stops improving for `patience` epochs."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        self.wait = 0
        self.cooldown_counter = 0
        self._saw_eval = False

    def _get_value(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0] if v else None
        return v

    def on_eval_begin(self, logs=None):
        # remember that an eval loop exists so the train-side hook
        # stays quiet for the rest of the run (fit fires on_epoch_end
        # BEFORE the epoch's eval pass)
        self._saw_eval = True

    def on_eval_end(self, logs=None):
        self._saw_eval = True
        self._maybe_reduce(self._get_value(logs))

    def on_epoch_end(self, epoch, logs=None):
        # train-metric monitoring ONLY when there is no eval loop:
        # with one, monitoring both hooks would advance wait/cooldown
        # twice per epoch and mix train and eval losses into `best`
        # (the double-firing bug). fit() declares the eval loop via the
        # `do_eval` callback param; `_saw_eval` covers callers that
        # drive evaluate() by hand without going through fit().
        if self.params.get("do_eval") or getattr(self, "_saw_eval",
                                                 False):
            return
        if self.monitor in (logs or {}):
            self._maybe_reduce(self._get_value(logs))

    def _maybe_reduce(self, value):
        if value is None:
            return
        if self.better(value, self.best):
            self.best = value
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            # in cooldown: no waiting, no reductions
            self.cooldown_counter -= 1
            self.wait = 0
            return
        self.wait += 1
        if self.wait < self.patience:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        from ..optimizer.lr import LRScheduler as Sched
        lr = opt._learning_rate
        if isinstance(lr, Sched):
            new = max(float(lr.last_lr) * self.factor, self.min_lr)
            lr.base_lr = new
            lr.last_lr = new
        else:
            new = max(float(lr) * self.factor, self.min_lr)
            opt._learning_rate = new
        if self.verbose:
            print(f"ReduceLROnPlateau: lr -> {new:.3e}")
        self.wait = 0
        self.cooldown_counter = self.cooldown


class WandbCallback(Callback):
    """`hapi/callbacks.py WandbCallback` parity: logs train/eval scalars
    to Weights & Biases. Requires the `wandb` package (same contract as
    the reference: ModuleNotFoundError at construction without it)."""

    def __init__(self, project=None, entity=None, name=None, dir=None,
                 mode=None, job_type=None, **kwargs):
        super().__init__()
        try:
            import wandb
        except ImportError as e:
            raise ModuleNotFoundError(
                "WandbCallback requires `pip install wandb`") from e
        self.wandb = wandb
        self.run = None
        self._kwargs = dict(project=project, entity=entity, name=name,
                            dir=dir, mode=mode, job_type=job_type,
                            **kwargs)
        self._step = 0

    def on_train_begin(self, logs=None):
        if self.run is None:
            self.run = self.wandb.init(
                **{k: v for k, v in self._kwargs.items()
                   if v is not None})

    def _log(self, prefix, logs):
        payload = {f"{prefix}/{k}": float(np.asarray(v).reshape(-1)[0])
                   for k, v in (logs or {}).items()
                   if not isinstance(v, str)}
        if payload and self.run is not None:
            self.run.log(payload, step=self._step)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._log("train", logs)

    def on_eval_end(self, logs=None):
        self._log("eval", logs)

    def on_train_end(self, logs=None):
        if self.run is not None:
            self.run.finish()
            self.run = None
