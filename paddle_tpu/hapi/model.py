"""paddle.Model — the Keras-like high-level API.

Parity: `python/paddle/hapi/model.py:1016` (`Model`), `fit:1708`,
`prepare:1631`, `DynamicGraphAdapter.train_batch:783`,
`prepare_distributed_context:202`.

TPU-native execution: `train_batch` runs a whole-step compiled executable
(forward+backward+fused update in one donated jax.jit — jit/trainer.py)
instead of per-op eager dispatch; this is where the reference needed the
static Program path for speed. Falls back to pure eager when tracing fails
(data-dependent python control flow in the model).
"""
from __future__ import annotations

import os
import pickle
import warnings

import numpy as np

from ..core.tensor import Tensor
from ..core import autograd
from .. import ops
from ..io import DataLoader
from ..jit.trainer import CompiledTrainStep, CompiledEvalStep
from .callbacks import config_callbacks


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = shape
        self.dtype = dtype
        self.name = name


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _arrays(batch):
    import jax
    out = []
    for b in _to_list(batch):
        if isinstance(b, Tensor):
            out.append(b._data)
        elif isinstance(b, jax.Array):
            out.append(b)   # device-resident (DeviceCacheLoader): keep
        else:
            out.append(np.asarray(b))
    return out


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._eval_step = None
        self._jit_ok = True
        self._group_ok = [True]  # grouped-dispatch health (fit)
        self.stop_training = False

    # ------------------------------------------------------------ prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        self._train_step = None
        self._eval_step = None
        self._dist_mesh = None
        # amp_configs parity: {'level': 'O1'|'O2', 'dtype': ...} or 'O2'
        if amp_configs:
            from .. import amp as amp_mod
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            level = amp_configs.get("level", "O1")
            dtype = amp_configs.get("dtype", "bfloat16")
            if level == "O2":
                amp_mod.decorate(self.network, level="O2", dtype=dtype)
            self._amp_level = level
            self._amp_dtype = dtype
        from ..parallel import env as dist_env
        if dist_env.get_world_size() > 1:
            dist_env.init_parallel_env()
            from ..parallel.topology import get_hybrid_communicate_group
            from ..parallel.mp_layers import place_model_on_mesh
            mesh = get_hybrid_communicate_group().mesh()
            if mesh.size > 1:
                self._dist_mesh = mesh
                place_model_on_mesh(self.network, mesh)
        return self

    # ------------------------------------------------------------- batch
    def _n_labels(self):
        return max(len(self._labels), 1)

    def _amp_context(self):
        """O1 auto_cast context from prepare(amp_configs=...) — must wrap
        the forward (incl. the compiled step's tracing call)."""
        if getattr(self, "_amp_level", None) == "O1":
            from .. import amp as amp_mod
            return amp_mod.auto_cast(level="O1",
                                     dtype=getattr(self, "_amp_dtype",
                                                   "bfloat16"))
        import contextlib
        return contextlib.nullcontext()

    def _maybe_shard(self, arrays):
        """Shard batch dim 0 over the dp mesh axis (DataParallel: the
        EagerReducer capability folds into the compiled step's GSPMD grad
        reduction)."""
        from ..jit.trainer import shard_batch_dp
        return shard_batch_dp(arrays, getattr(self, "_dist_mesh", None))

    def _train_batch_inner(self, inputs, labels, update=True):
        """Returns ([loss_tensor], metrics) WITHOUT host synchronisation
        (the fit loop materialises losses lazily at log points — a host
        round-trip per step costs ~0.3s through the TPU relay)."""
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        batch = self._maybe_shard(_arrays(inputs) + _arrays(labels))
        amp_ctx = self._amp_context()
        if self._jit_ok:
            try:
                if self._train_step is None:
                    self._train_step = CompiledTrainStep(
                        self.network, self._loss, self._optimizer,
                        n_labels=len(labels) or 1)
                with amp_ctx:  # active during first-call tracing (O1)
                    loss, outs = self._train_step.run(*batch)
                metrics = self._update_metrics(outs, labels)
                return [loss], metrics
            except Exception as e:  # fall back to eager once
                warnings.warn(
                    f"compiled train step failed ({type(e).__name__}: {e}); "
                    "falling back to eager execution")
                if self._train_step is not None:
                    # undo the ZeRO flat accumulator layout so the eager
                    # optimizer path sees logical shapes again
                    self._train_step.restore_accums()
                self._jit_ok = False
        # eager path (DynamicGraphAdapter.train_batch parity)
        with self._amp_context():
            outs = self.network(*[t if isinstance(t, Tensor) else Tensor(t)
                                  for t in inputs])
            outs_l = _to_list(outs)
            lbl = [t if isinstance(t, Tensor) else Tensor(t)
                   for t in labels]
            loss = self._loss(*outs_l, *lbl) if self._loss else outs_l[0]
        loss = loss.astype("float32") if loss.dtype != np.float32 else loss
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs_l, labels)
        return [loss], metrics

    def train_batch(self, inputs, labels=None, update=True):
        losses, metrics = self._train_batch_inner(inputs, labels, update)
        np_losses = [l.numpy() for l in losses]
        return np_losses if not metrics else (np_losses, metrics)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        batch = self._maybe_shard(_arrays(inputs) + _arrays(labels))
        if self._eval_step is None:
            self._eval_step = CompiledEvalStep(
                self.network, self._loss, n_labels=len(labels) or 1)
        loss, outs = self._eval_step.run(*batch)
        metrics = self._update_metrics(outs, labels)
        res = [loss.numpy()] if loss is not None else []
        return (res, metrics) if metrics else res

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = _to_list(inputs)
        with autograd.no_grad():
            outs = self.network(*[t if isinstance(t, Tensor) else Tensor(t)
                                  for t in inputs])
        return [o.numpy() for o in _to_list(outs)]

    def _update_metrics(self, outs, labels):
        metric_vals = []
        lbl = [t if isinstance(t, Tensor) else Tensor(t) for t in labels]
        for m in self._metrics:
            state = m.compute(*_to_list(outs), *lbl)
            r = m.update(*_to_list(state))
            metric_vals.append(r)
        return metric_vals

    # --------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DeviceCacheLoader
        if isinstance(train_data, (DataLoader, DeviceCacheLoader)):
            loader = train_data
        else:
            loader = DataLoader(train_data, batch_size=batch_size,
                                shuffle=shuffle, drop_last=drop_last,
                                num_workers=num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                verbose=verbose, save_freq=save_freq,
                                save_dir=save_dir,
                                metrics=self._metrics_name(),
                                do_eval=eval_data is not None)
        cbks.on_train_begin()
        # throughput timer (python/paddle/profiler/timer.py parity):
        # paddle.profiler.benchmark().step_info() reports reader/batch
        # cost + ips for this fit loop
        from ..profiler.timer import benchmark as _benchmark
        _bm = _benchmark()
        _bm.begin()
        self.stop_training = False
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            res = None
            # Step grouping: with no metrics and a static learning rate,
            # K consecutive steps run as ONE device dispatch (lax.scan
            # in CompiledTrainStep.run_many) — dispatching through the
            # TPU relay costs ~8 ms per call regardless of compute,
            # which capped small models at ~65 steps/s. Groups never
            # span a log point, so logged losses are exact for their
            # step. Per-step LR schedulers disable grouping (each step
            # must see its own lr); callback begin/end pairs fire in
            # order at flush time (after the async dispatch — same
            # visibility as the per-step path, whose device work has not
            # finished at on_train_batch_end either).
            pending = []       # [(step, batch_arrays)]
            last_loss = [None]
            group_ok = self._group_ok   # persists across epochs

            def flush():
                if not pending:
                    return
                steps_, arrs_ = zip(*pending)
                pending.clear()
                try:
                    with self._amp_context():  # O1 must wrap tracing
                        losses = self._train_step.run_many(
                            list(arrs_),
                            mesh=getattr(self, "_dist_mesh", None))
                except Exception as e:
                    # ADVICE r4 #4: the grouped executable donates
                    # params/accums — if it failed at EXECUTION time the
                    # buffers may already be consumed, and a per-step
                    # replay would read deleted arrays. Detect and raise
                    # cleanly instead of crashing mid-replay.
                    if any(getattr(p._data, "is_deleted",
                                   lambda: False)()
                           for p in self._train_step.p_tensors):
                        raise RuntimeError(
                            "grouped train step failed after buffer "
                            "donation; parameter state was consumed and "
                            "cannot be replayed. Re-initialise the "
                            "model/optimizer (or set "
                            "model._fit_group_max = 1 to train "
                            "per-step)") from e
                    warnings.warn(
                        f"grouped train steps failed ({type(e).__name__}:"
                        f" {e}); replaying per-step and disabling "
                        "grouping")
                    group_ok[0] = False
                    for s, arrs in zip(steps_, arrs_):
                        cbks.on_train_batch_begin(s)
                        n_in = len(arrs) - self._n_labels()
                        res = self._train_batch_inner(
                            list(arrs[:n_in]), list(arrs[n_in:]))
                        last_loss[0] = ("plain", res[0][0])
                        if s % max(log_freq, 1) == 0:
                            cbks.on_train_batch_end(s,
                                                    self._make_logs(res))
                        else:
                            cbks.on_train_batch_end(s, {})
                    return
                # keep the stacked losses; index lazily (an eager slice
                # is a device dispatch — only pay it at log points)
                last_loss[0] = ("stacked", losses)
                for i, s in enumerate(steps_):
                    cbks.on_train_batch_begin(s)
                    if s % max(log_freq, 1) == 0:
                        lg = self._make_logs(([losses[i]], []))
                        cbks.on_train_batch_end(s, lg)
                    else:
                        cbks.on_train_batch_end(s, {})

            # group size cap: larger groups amortise per-dispatch relay
            # latency further but compile one executable per distinct
            # size — raise via model._fit_group_max for small models on
            # high-latency links
            group_max = getattr(self, "_fit_group_max", 8)
            shapes = None
            static_lr = not hasattr(
                getattr(self._optimizer, "_learning_rate", 0.0), "step")
            for step, batch in enumerate(loader):
                _bm.after_reader()
                ins, lbs = self._split_batch(batch)
                _bs = next((int(x.shape[0]) for x in _to_list(ins)
                            if hasattr(x, "shape") and len(x.shape)), 1)
                can_group = (group_ok[0] and self._jit_ok
                             and not self._metrics and static_lr
                             and self._train_step is not None
                             and not self._train_step.input_grads
                             and not self._train_step._offload)
                if can_group:
                    arrs = _arrays(ins) + _arrays(lbs)
                    bshapes = tuple(getattr(a, "shape", ()) for a in arrs)
                    if pending and bshapes != shapes:
                        flush()
                    shapes = bshapes
                    pending.append((step, arrs))
                    is_last = (num_iters is not None
                               and step + 1 >= num_iters)
                    next_is_log = (step + 1) % max(log_freq, 1) == 0
                    if len(pending) >= group_max or next_is_log or \
                            is_last:
                        _n = len(pending)
                        flush()
                        _bm.after_step(num_samples=_n * _bs,
                                       num_steps=_n)
                    if is_last:
                        break
                    continue
                flush()
                cbks.on_train_batch_begin(step)
                res = self._train_batch_inner(ins, lbs)
                _bm.after_step(num_samples=_bs)
                last_loss[0] = ("plain", res[0][0])
                # lazy logging: only materialise the loss (device->host
                # sync) at log points so steps pipeline on the device;
                # non-log steps hand callbacks an EMPTY dict rather than
                # stale values (per-step consumers set log_freq=1)
                if step % max(log_freq, 1) == 0:
                    logs = self._make_logs(res)
                    cbks.on_train_batch_end(step, logs)
                else:
                    cbks.on_train_batch_end(step, {})
                if num_iters is not None and step + 1 >= num_iters:
                    break
            flush()
            if last_loss[0] is not None:
                kind, val = last_loss[0]
                logs = self._make_logs(
                    ([val[-1] if kind == "stacked" else val], []))
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              _inner=True)
            if self.stop_training:
                break
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _inner=False):
        from ..io import DeviceCacheLoader
        if isinstance(eval_data, (DataLoader, DeviceCacheLoader)):
            loader = eval_data
        else:
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, verbose=verbose,
            metrics=self._metrics_name())
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbs = self._split_batch(batch)
            res = self.eval_batch(ins, lbs)
            logs = self._make_logs(res, prefix="eval_")
            cbks.on_eval_batch_end(step, logs)
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io import DeviceCacheLoader
        if isinstance(test_data, (DataLoader, DeviceCacheLoader)):
            loader = test_data
        else:
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, predict=True)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    def _split_batch(self, batch, predict=False):
        batch = _to_list(batch)
        if predict or self._loss is None:
            if self._inputs:
                return batch[:len(self._inputs)], []
            # no spec: feed as many tensors as network.forward accepts
            import inspect
            try:
                sig = inspect.signature(self.network.forward)
                n_in = len([p for p in sig.parameters.values()
                            if p.kind in (p.POSITIONAL_ONLY,
                                          p.POSITIONAL_OR_KEYWORD)
                            and p.default is p.empty])
                if 0 < n_in < len(batch):
                    return batch[:n_in], []
            except (TypeError, ValueError):
                pass
            return batch, []
        n_lab = self._n_labels()
        return batch[:-n_lab], batch[-n_lab:]

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _make_logs(self, res, prefix=""):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
        else:
            losses, metrics = res, []
        if losses:
            logs[prefix + "loss"] = float(np.asarray(losses[0]).reshape(-1)[0])
        idx = 0
        for m in self._metrics:
            names = m.name()
            names = names if isinstance(names, list) else [names]
            acc = m.accumulate()
            accs = acc if isinstance(acc, list) else [acc]
            for n, a in zip(names, accs):
                logs[prefix + n] = a
            idx += 1
        return logs

    # ------------------------------------------------------------- state
    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        from ..framework_io import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework_io import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(pload(opt_path))

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        info = {"total_params": n_params,
                "trainable_params": sum(
                    p.size for p in self.network.parameters()
                    if not p.stop_gradient)}
        print(f"Total params: {n_params}")
        return info
