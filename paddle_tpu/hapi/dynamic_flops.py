"""`paddle.flops` parity (`python/paddle/hapi/dynamic_flops.py`):
per-layer FLOP/param counting by a shape-capturing forward pass."""
from __future__ import annotations

import numpy as np


def _count(layer, in_shape, out_shape):
    """FLOPs for one leaf layer given captured shapes (2*MAC where a MAC
    convention exists — the reference counts multiply-adds as 2)."""
    import paddle_tpu.nn as nn
    n_out = int(np.prod(out_shape))
    if isinstance(layer, nn.Linear):
        return 2 * n_out * layer.weight.shape[0]
    if layer.__class__.__name__.startswith("Conv"):
        w = layer.weight.shape          # [out_c, in_c/groups, *k]
        k = int(np.prod(w[1:]))
        return 2 * n_out * k
    if layer.__class__.__name__ in ("BatchNorm1D", "BatchNorm2D",
                                    "BatchNorm3D", "LayerNorm",
                                    "GroupNorm", "InstanceNorm2D"):
        return 2 * n_out
    if layer.__class__.__name__ in ("ReLU", "GELU", "Sigmoid", "Tanh",
                                    "Softmax", "LeakyReLU", "ReLU6",
                                    "Hardswish", "Hardsigmoid", "SiLU"):
        return n_out
    if "Pool" in layer.__class__.__name__:
        return n_out
    return 0


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Run one forward on zeros of `input_size`, hook every leaf layer,
    and report total FLOPs (also returns it). `custom_ops`: dict
    layer_class -> fn(layer, in_shape, out_shape) -> flops."""
    import paddle_tpu as paddle

    rows = []
    hooks = []
    custom_ops = custom_ops or {}

    def make_hook(layer):
        def hook(lyr, inputs, output):
            in_shape = tuple(inputs[0].shape) if inputs else ()
            out = output[0] if isinstance(output, (tuple, list)) else output
            out_shape = tuple(out.shape)
            fn = custom_ops.get(type(lyr))
            f = (fn(lyr, in_shape, out_shape) if fn
                 else _count(lyr, in_shape, out_shape))
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr.parameters(include_sublayers=False))
            rows.append((type(lyr).__name__, in_shape, out_shape,
                         n_params, f))
        return hook

    for lyr in net.sublayers(include_self=True):
        if not list(lyr.children()):            # leaves only
            hooks.append(lyr.register_forward_post_hook(make_hook(lyr)))
    was_training = net.training
    net.eval()
    try:
        x = paddle.zeros(list(input_size), dtype="float32")
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    total = sum(r[4] for r in rows)
    total_params = sum(r[3] for r in rows)
    if print_detail:
        print(f"{'Layer':<20}{'Input':<22}{'Output':<22}"
              f"{'Params':>10}{'FLOPs':>14}")
        for name, i, o, p, f in rows:
            print(f"{name:<20}{str(i):<22}{str(o):<22}{p:>10}{f:>14}")
        print(f"Total params: {total_params}  Total FLOPs: {total}")
    return total
