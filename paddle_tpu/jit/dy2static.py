"""dy2static: AST transforms for data-dependent Python control flow.

Parity: the reference's dygraph_to_static transformer stack
(`fluid/dygraph/dygraph_to_static/ast_transformer.py` — IfElse / Loop /
break-continue transformers feeding `program_translator.py:1001`).
TPU-native re-design: instead of lowering to static-graph
`cond`/`while_loop` *ops*, the rewritten source calls the runtime helpers
below, which dispatch per call —

  - concrete predicate (eager, or a trace-time constant): plain Python
    branch/loop, zero overhead, side effects allowed;
  - traced predicate (inside jax.jit): `lax.cond` / `lax.while_loop`, so
    a model whose `if`/`while` depends on tensor VALUES still compiles
    into one XLA program instead of falling back to eager.

Supported subset (transformed): `if`/`elif`/`else` whose branches only
assign; `while`; `for i in range(...)`; `if <cond>: break` as the first
statement of a loop body (folded into the loop condition). Anything else
(return inside a branch, general break/continue, try/with, …) is left as
ordinary Python — static control flow still traces fine; genuinely
data-dependent cases keep the documented eager fallback.

Like `lax.cond` (and the reference's trace-both-branches behavior),
Python side effects in both branches of a TRACED `if` execute at trace
time.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class _Undef:
    __slots__ = ()

    def __repr__(self):
        return "<dy2static UNDEF>"


UNDEF = _Undef()


def _val(x):
    return x._data if isinstance(x, Tensor) else x


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _rewrap(arr):
    return Tensor(arr)


def cond(pred, true_fn, false_fn):
    """Runtime for a transformed `if`: fns take no args (outer values are
    captured as default args) and return the tuple of assigned names."""
    p = _val(pred)
    if not _is_tracer(p):
        return true_fn() if bool(p) else false_fn()

    def wrap(fn):
        def inner(_):
            out = fn()
            vals = []
            for o in out:
                v = _val(o)
                if isinstance(v, _Undef):
                    raise ValueError(
                        "dy2static: a variable assigned in only one "
                        "branch of a traced `if` must be initialised "
                        "before the `if`")
                vals.append(v)
            return tuple(vals)
        return inner

    res = jax.lax.cond(p, wrap(true_fn), wrap(false_fn), None)
    return tuple(_rewrap(r) for r in res)


def while_loop(cond_fn, body_fn, init_vals):
    """Runtime for a transformed `while`/`for`: cond_fn/body_fn take the
    loop vars positionally; body_fn returns the updated tuple."""
    for v in init_vals:
        if isinstance(v, _Undef):
            raise ValueError(
                "dy2static: loop variables must be initialised before a "
                "transformed loop")
    c0 = _val(cond_fn(*init_vals))
    traced = _is_tracer(c0) or any(_is_tracer(_val(v)) for v in init_vals)
    if not traced:
        vals = tuple(init_vals)
        while bool(_val(cond_fn(*vals))):
            vals = tuple(body_fn(*vals))
        return vals

    init = tuple(jnp.asarray(_val(v)) for v in init_vals)

    def c(arrs):
        return _val(cond_fn(*[_rewrap(a) for a in arrs]))

    def b(arrs):
        out = body_fn(*[_rewrap(a) for a in arrs])
        return tuple(jnp.asarray(_val(o)) for o in out)

    res = jax.lax.while_loop(c, b, init)
    return tuple(_rewrap(r) for r in res)


def range_cond(i, stop, step):
    """`for i in range(...)` continuation test, sign-aware on step."""
    iv, sv, st = _val(i), _val(stop), _val(step)
    out = jnp.where(st > 0, iv < sv, iv > sv)
    return _rewrap(out) if (_is_tracer(out) or isinstance(out, Tensor)) \
        else bool(out)


def logical_and(a, b):
    av, bv = _val(a), _val(b)
    if not (_is_tracer(av) or _is_tracer(bv)):
        return bool(av) and bool(bv)
    return _rewrap(jnp.logical_and(av, bv))


def logical_not(a):
    av = _val(a)
    if not _is_tracer(av):
        return not bool(av)
    return _rewrap(jnp.logical_not(av))


def range3(*args):
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args[0], args[1], args[2]


# ------------------------------------------------------------ transforms

_SIMPLE_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
                 ast.If, ast.For, ast.While, ast.Pass)


def _mark_generated(stmts):
    for s in stmts:
        s._dy2s_generated = True
    return stmts


class _RenameVar(ast.NodeTransformer):
    def __init__(self, old, new):
        self.old, self.new = old, new

    def visit_Name(self, node):
        if node.id == self.old and isinstance(node.ctx, ast.Load):
            return ast.copy_location(_name(self.new), node)
        return node


def _assigned_names(stmts):
    """Names (re)bound anywhere in these statements, not descending into
    nested function/class definitions."""
    names = []

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            if node.id not in names:
                names.append(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    for s in stmts:
        visit(s)
    return names


def _transformable(stmts):
    # statements this transformer itself generated (UNDEF preambles,
    # branch helper defs, _jst calls) are always acceptable — without
    # this, an already-rewritten inner `elif` blocks the outer `if`
    return all(isinstance(s, _SIMPLE_STMTS)
               or getattr(s, "_dy2s_generated", False) for s in stmts)


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name,
                         ctx=ast.Load())


def _undef_preamble(var):
    """try: v \n except NameError/UnboundLocalError: v = _jst.UNDEF"""
    return ast.Try(
        body=[ast.Expr(value=_name(var))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[_name("NameError"),
                                 _name("UnboundLocalError")],
                           ctx=ast.Load()),
            name=None,
            body=[ast.Assign(targets=[_name(var, ast.Store())],
                             value=_jst_attr("UNDEF"))])],
        orelse=[], finalbody=[])


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[_name(n) for n in names], ctx=ast.Load()))


def _assign_tuple(names, value):
    return ast.Assign(
        targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                           ctx=ast.Store())],
        value=value)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _uid(self):
        self._counter += 1
        return self._counter

    # -- don't descend into nested defs/lambdas: they run as plain python
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if not (_transformable(node.body)
                and _transformable(node.orelse or [ast.Pass()])):
            return node
        outs = _assigned_names(node.body + node.orelse)
        if not outs:
            return node
        uid = self._uid()
        tname, fname = f"__dy2s_true_{uid}", f"__dy2s_false_{uid}"
        # outer values captured via default args so aug-assigns/reads of
        # the output vars resolve inside the generated functions
        arg_defaults = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in outs],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[_name(n) for n in outs])
        tdef = ast.FunctionDef(
            name=tname, args=arg_defaults,
            body=list(node.body) + [_ret_tuple(outs)],
            decorator_list=[], returns=None)
        fdef = ast.FunctionDef(
            name=fname, args=arg_defaults,
            body=list(node.orelse or [ast.Pass()]) + [_ret_tuple(outs)],
            decorator_list=[], returns=None)
        call = ast.Call(func=_jst_attr("cond"),
                        args=[node.test, _name(tname), _name(fname)],
                        keywords=[])
        stmts = [_undef_preamble(n) for n in outs]
        stmts += [tdef, fdef, _assign_tuple(outs, call)]
        return _mark_generated(stmts)

    def _loop_helpers(self, loop_vars, body_stmts, test_expr, uid):
        cname, bname = f"__dy2s_cond_{uid}", f"__dy2s_body_{uid}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in loop_vars],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        cdef = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=test_expr)],
            decorator_list=[], returns=None)
        bdef = ast.FunctionDef(
            name=bname, args=args,
            body=body_stmts + [_ret_tuple(loop_vars)],
            decorator_list=[], returns=None)
        call = ast.Call(
            func=_jst_attr("while_loop"),
            args=[_name(cname), _name(bname),
                  ast.Tuple(elts=[_name(n) for n in loop_vars],
                            ctx=ast.Load())],
            keywords=[])
        return [cdef, bdef, _assign_tuple(loop_vars, call)]

    @staticmethod
    def _fold_leading_break(body, test):
        """`while c: if b: break; rest` == `while c and not b: rest`."""
        if body and isinstance(body[0], ast.If) and not body[0].orelse \
                and len(body[0].body) == 1 \
                and isinstance(body[0].body[0], ast.Break):
            # python `and`/`not` would force bool() on tracers — use the
            # tracer-aware logical helpers
            folded = ast.Call(
                func=_jst_attr("logical_and"),
                args=[test,
                      ast.Call(func=_jst_attr("logical_not"),
                               args=[body[0].test], keywords=[])],
                keywords=[])
            return body[1:], folded
        return body, test

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node
        body, test = self._fold_leading_break(node.body, node.test)
        if not _transformable(body):
            return node
        loop_vars = _assigned_names(body)
        if not loop_vars:
            return node
        uid = self._uid()
        stmts = [_undef_preamble(n) for n in loop_vars]
        stmts += self._loop_helpers(loop_vars, body, test, uid)
        return _mark_generated(stmts)

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords):
            return node
        uid = self._uid()
        i = node.target.id
        # internal counter `ctr` drives the loop; the USER's variable is
        # assigned from it at body start, so after the loop it holds the
        # last ITERATED value (python for semantics), not one past it
        ctr = f"__dy2s_i_{uid}"
        stop_v, step_v = f"__dy2s_stop_{uid}", f"__dy2s_step_{uid}"
        start_assign = _assign_tuple(
            [ctr, stop_v, step_v],
            ast.Call(func=_jst_attr("range3"), args=list(it.args),
                     keywords=[]))
        test = ast.Call(func=_jst_attr("range_cond"),
                        args=[_name(ctr), _name(stop_v), _name(step_v)],
                        keywords=[])
        body, test = self._fold_leading_break(node.body, test)
        # the folded break test runs in the loop CONDITION, where the
        # user's variable still holds the previous iteration's value —
        # the internal counter is the current one, so reads of the loop
        # var inside the folded test must use the counter
        test = _RenameVar(i, ctr).visit(test)
        if not _transformable(body):
            return node
        set_user = ast.Assign(targets=[_name(i, ast.Store())],
                              value=_name(ctr))
        incr = ast.AugAssign(target=_name(ctr, ast.Store()),
                             op=ast.Add(), value=_name(step_v))
        body = [set_user] + body + [incr]
        loop_vars = [ctr, i] + [n for n in _assigned_names(body)
                                if n not in (ctr, i)]
        stmts = [start_assign,
                 # seed the user's var so the traced carry is defined even
                 # for range(0) (python would NameError on a later read;
                 # we leave it at start — documented approximation)
                 ast.Assign(targets=[_name(i, ast.Store())],
                            value=_name(ctr))]
        stmts += [_undef_preamble(n) for n in loop_vars
                  if n not in (ctr, i)]
        stmts += self._loop_helpers(loop_vars, body, test, uid)
        return _mark_generated(stmts)


_cache = {}


def transform_function(fn):
    """Rewrite data-dependent control flow in `fn` (a function or bound
    method) into _jst.cond/while_loop calls. Returns the original on any
    failure (source unavailable, unsupported constructs, …)."""
    if isinstance(fn, types.MethodType):
        new = transform_function(fn.__func__)
        return types.MethodType(new, fn.__self__)
    if fn in _cache:
        return _cache[fn]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            raise ValueError("not a function definition")
        fdef.decorator_list = []
        new_body = []
        tr = _ControlFlowTransformer()
        for stmt in fdef.body:
            out = tr.visit(stmt)
            new_body.extend(out if isinstance(out, list) else [out])
        if tr._counter == 0:
            _cache[fn] = fn  # nothing to rewrite
            return fn
        fdef.body = new_body
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        glb = dict(fn.__globals__)
        # re-expose the original closure as globals (exec'd functions
        # have no closure cells)
        if fn.__closure__:
            for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
                try:
                    glb[name] = cell.cell_contents
                except ValueError:
                    pass
        import paddle_tpu.jit.dy2static as _jst_mod
        glb["_jst"] = _jst_mod
        loc = {}
        exec(code, glb, loc)
        new_fn = loc[fdef.name]
        new_fn = functools.wraps(fn)(new_fn)
        _cache[fn] = new_fn
        return new_fn
    except Exception:
        _cache[fn] = fn
        return fn
