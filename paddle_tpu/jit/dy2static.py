"""dy2static: AST transforms for data-dependent Python control flow.

Parity: the reference's dygraph_to_static transformer stack
(`fluid/dygraph/dygraph_to_static/ast_transformer.py` — IfElse / Loop /
break-continue transformers feeding `program_translator.py:1001`).
TPU-native re-design: instead of lowering to static-graph
`cond`/`while_loop` *ops*, the rewritten source calls the runtime helpers
below, which dispatch per call —

  - concrete predicate (eager, or a trace-time constant): plain Python
    branch/loop, zero overhead, side effects allowed;
  - traced predicate (inside jax.jit): `lax.cond` / `lax.while_loop`, so
    a model whose `if`/`while` depends on tensor VALUES still compiles
    into one XLA program instead of falling back to eager.

Supported subset (transformed): `if`/`elif`/`else` whose branches only
assign; `while`; `for i in range(...)` AND non-range `for x in seq`
(indexed rewrite over `_jst.seq_len`; tensors iterate dim-0 slices
under trace); `break`/`continue` anywhere in a loop body, possibly
nested in `if`s (flag rewriting: the loop condition folds in `not
break_flag`, statements after a potential break/continue are guarded —
break_continue_transformer.py parity); `return` inside branches
(single-exit rewriting by else-hoisting into a result var —
return_transformer.py parity) and inside loops (shared flag + break +
guarded return); control flow nested inside `with`/`try` bodies (the
context/handler stays python — trace-time semantics — while the inner
`if`/`for`/`while` lower to lax; tested). Still python (eager
fallback): `return` statements physically inside a `with`/`try` block
when code follows the block, and partially-returning nested branches
past the else-hoisting size budget.

Like `lax.cond` (and the reference's trace-both-branches behavior),
Python side effects in both branches of a TRACED `if` execute at trace
time.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class _Undef:
    __slots__ = ()

    def __repr__(self):
        return "<dy2static UNDEF>"


UNDEF = _Undef()


def _val(x):
    return x._data if isinstance(x, Tensor) else x


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _rewrap(arr):
    return Tensor(arr)


class _Poison:
    """Stand-in for a variable assigned in only ONE branch of a traced
    `if` (python would UnboundLocalError on the other path; a traced
    cond can't be path-dependent). Any actual USE raises with the
    variable's name; carrying it dead is free — so branch-local
    temporaries no longer block tracing."""
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def _raise(self, *a, **k):
        raise ValueError(
            f"dy2static: variable '{self.name}' was assigned in only one "
            "branch of a traced `if` and then read afterwards; "
            "initialise it before the `if` so both paths define it")

    def __repr__(self):
        return f"<dy2static poisoned '{self.name}'>"

    __getattr__ = __call__ = __getitem__ = __bool__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __neg__ = __iter__ = __array__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = __eq__ = __ne__ = _raise
    __hash__ = object.__hash__


def cond(pred, true_fn, false_fn, names=None, cur_vals=None, both=None):
    """Runtime for a transformed `if`: fns take no args (outer values are
    captured as default args) and return the tuple of assigned names.

    Traced predicate: a slot is undefined on some path iff its CURRENT
    value is UNDEF/poisoned and the transformer says it is not assigned
    in both branches (`both`, static). lax.cond carries only the slots
    every path defines; the rest come back poisoned (error on use, not
    on existence) — so dead branch-local temporaries never block
    tracing."""
    p = _val(pred)
    if not _is_tracer(p):
        return true_fn() if bool(p) else false_fn()

    if cur_vals is not None and both is not None:
        n = len(cur_vals)
        undef = {i for i in range(n)
                 if isinstance(cur_vals[i], (_Undef, _Poison))
                 and not both[i]}
    else:  # legacy probe path (direct cond() callers)
        t_probe = true_fn()
        f_probe = false_fn()
        n = len(t_probe)
        undef = {i for i in range(n)
                 if isinstance(_val(t_probe[i]), _Undef)
                 or isinstance(_val(f_probe[i]), _Undef)}
    live = [i for i in range(n) if i not in undef]

    def wrap(fn):
        def inner(_):
            out = fn()
            return tuple(_val(out[i]) for i in live)
        return inner

    res = jax.lax.cond(p, wrap(true_fn), wrap(false_fn), None)
    merged, j = [], 0
    for i in range(n):
        if i in undef:
            merged.append(_Poison(names[i] if names else f"<slot {i}>"))
        else:
            merged.append(_rewrap(res[j]))
            j += 1
    return tuple(merged)


def _check_loop_init(init_vals):
    """Traced loops need every carry defined up front; eager python
    loops may assign vars inside the body (read-before-assign raises at
    the read through _Poison, faithful python semantics)."""
    for v in init_vals:
        if isinstance(v, _Undef):
            raise ValueError(
                "dy2static: loop variables must be initialised before a "
                "transformed (traced) loop")
        if isinstance(v, _Poison):
            v._raise()


def _lax_carry_ok(v):
    """Can this value ride a lax loop carry?  Layer objects / UNDEF
    can't — loops over them must unroll pythonically (possible whenever
    the loop condition is concrete, e.g. `for blk in self.blocks`)."""
    if isinstance(v, (_Undef, _Poison)):
        return False
    x = _val(v)
    if _is_tracer(x) or isinstance(x, (bool, int, float, jax.Array)):
        return True
    try:
        jnp.asarray(x)
        return True
    except (TypeError, ValueError):
        return False


def _loop_dispatch(cond_fn, init_vals):
    """(traced, c0): traced -> lower to lax; else python loop (which,
    under an outer jit trace with a CONCRETE condition, simply unrolls
    — required when the carry holds non-array objects)."""
    c0 = _val(cond_fn(*init_vals))
    if _is_tracer(c0):
        return True, c0
    if all(_lax_carry_ok(v) for v in init_vals) and \
            any(_is_tracer(_val(v)) for v in init_vals):
        return True, c0
    return False, c0


def while_loop(cond_fn, body_fn, init_vals):
    """Runtime for a transformed `while`/`for`: cond_fn/body_fn take the
    loop vars positionally; body_fn returns the updated tuple.

    Starts as a python loop whenever the condition is concrete (which,
    under an outer trace, unrolls — required for non-arrayable carries
    like Layer objects); escalates to lax.while_loop from the CURRENT
    state the moment the condition or an arrayable carry turns traced
    (e.g. a break flag assigned from a traced cond)."""
    vals = tuple(init_vals)
    while True:
        traced, c = _loop_dispatch(cond_fn, vals)
        if traced:
            break
        if not bool(c):
            return vals
        vals = tuple(body_fn(*vals))
    init_vals = vals
    _check_loop_init(init_vals)

    init = tuple(jnp.asarray(_val(v)) for v in init_vals)

    def c(arrs):
        return _val(cond_fn(*[_rewrap(a) for a in arrs]))

    def b(arrs):
        out = body_fn(*[_rewrap(a) for a in arrs])
        return tuple(jnp.asarray(_val(o)) for o in out)

    res = jax.lax.while_loop(c, b, init)
    return tuple(_rewrap(r) for r in res)


def trip_count(start, stop, step):
    """Static trip count of range(start, stop, step), or None when any
    bound is traced (dynamic)."""
    s, e, st = _val(start), _val(stop), _val(step)
    if any(_is_tracer(v) for v in (s, e, st)):
        return None
    s, e, st = int(s), int(e), int(st)
    if st == 0:
        return 0
    if st > 0:
        return max(0, (e - s + st - 1) // st)
    return max(0, (s - e + (-st) - 1) // (-st))


def bounded_while(cond_fn, body_fn, init_vals, max_trips):
    """while_loop with a STATIC trip bound: lowers to a masked lax.scan
    (each step keeps the old carry once the condition goes false), which
    — unlike lax.while_loop — is reverse-mode differentiable, so
    data-dependent `for`/`break` loops work in training steps."""
    if max_trips is None:
        return while_loop(cond_fn, body_fn, init_vals)
    # python start + mid-loop lax escalation (see while_loop); each
    # concrete iteration consumed shrinks the remaining scan bound
    vals = tuple(init_vals)
    done = 0
    while True:
        traced, c = _loop_dispatch(cond_fn, vals)
        if traced:
            break
        if not bool(c):
            return vals
        vals = tuple(body_fn(*vals))
        done += 1
    init_vals = vals
    max_trips = max(0, int(max_trips) - done)
    _check_loop_init(init_vals)
    init = tuple(jnp.asarray(_val(v)) for v in init_vals)
    # probe one body application to learn the steady-state carry dtypes
    # (e.g. `s = 0` then `s = s + x.sum()` promotes int->float); the
    # probe ops are pure and DCE'd by XLA
    probe = body_fn(*[_rewrap(a) for a in init])
    init = tuple(
        a.astype(jnp.result_type(a, jnp.asarray(_val(p)).dtype))
        for a, p in zip(init, probe))

    def step(carry, _):
        active = _val(cond_fn(*[_rewrap(a) for a in carry]))
        out = body_fn(*[_rewrap(a) for a in carry])
        new = []
        for o, a, in zip(out, carry):
            oa = jnp.asarray(_val(o))
            if oa.dtype != a.dtype or oa.shape != a.shape:
                # loud, like lax.while_loop's carry check — a silent
                # astype would truncate (float sum into int carry)
                raise TypeError(
                    "dy2static: loop variable changed "
                    f"dtype/shape across iterations ({a.dtype}"
                    f"{a.shape} -> {oa.dtype}{oa.shape}); keep loop "
                    "variables stable (e.g. initialise accumulators "
                    "with the right dtype)")
            new.append(jnp.where(active, oa, a))
        return tuple(new), None

    res, _ = jax.lax.scan(step, init, None, length=int(max_trips))
    return tuple(_rewrap(r) for r in res)


def as_seq(seq):
    """Materialise a `for x in seq` iterable once so it can be indexed
    (dict views, generators); tensors and real sequences pass through."""
    if isinstance(seq, (Tensor, jax.Array, list, tuple, str)):
        return seq
    if hasattr(seq, "__getitem__") and hasattr(seq, "__len__"):
        return seq
    return list(seq)


def seq_len(seq):
    """Static length of a `for x in seq` iterable: dim-0 for tensors
    (paddle iterates over dim-0 slices), len() otherwise."""
    if isinstance(seq, Tensor) or isinstance(seq, jax.Array):
        return int(seq.shape[0])
    return len(seq)


def seq_get(seq, i):
    """Index the iterable for the transformed non-range `for`. Python
    sequences need a concrete index (they are only reached on the eager
    path); tensors accept traced indices (lax gather)."""
    iv = _val(i)
    if isinstance(seq, Tensor):
        return seq[iv if not isinstance(iv, int) else int(iv)]
    if isinstance(seq, jax.Array):
        return _rewrap(seq[iv])
    return seq[int(iv)]


def range_cond(i, stop, step):
    """`for i in range(...)` continuation test, sign-aware on step.

    Concrete operands MUST produce a python bool even under an active
    jit trace (jnp ops on constants return tracers there): a concrete
    condition is what lets loops with non-arrayable carries (e.g.
    `for layer in self.layers`) unroll pythonically instead of failing
    the lax-carry check."""
    iv, sv, st = _val(i), _val(stop), _val(step)
    if not any(_is_tracer(v) for v in (iv, sv, st)):
        return bool(iv < sv) if st > 0 else bool(iv > sv)
    out = jnp.where(st > 0, iv < sv, iv > sv)
    return _rewrap(out) if (_is_tracer(out) or isinstance(out, Tensor)) \
        else bool(out)


def logical_and(a, b):
    av, bv = _val(a), _val(b)
    if not (_is_tracer(av) or _is_tracer(bv)):
        return bool(av) and bool(bv)
    return _rewrap(jnp.logical_and(av, bv))


def logical_not(a):
    av = _val(a)
    if not _is_tracer(av):
        return not bool(av)
    return _rewrap(jnp.logical_not(av))


def logical_or(a, b):
    av, bv = _val(a), _val(b)
    if not (_is_tracer(av) or _is_tracer(bv)):
        return bool(av) or bool(bv)
    return _rewrap(jnp.logical_or(av, bv))


def range3(*args):
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args[0], args[1], args[2]


# ------------------------------------------------------------ transforms

_SIMPLE_STMTS = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
                 ast.If, ast.For, ast.While, ast.Pass)


def _mark_generated(stmts):
    for s in stmts:
        s._dy2s_generated = True
    return stmts


class _RenameVar(ast.NodeTransformer):
    def __init__(self, old, new):
        self.old, self.new = old, new

    def visit_Name(self, node):
        if node.id == self.old and isinstance(node.ctx, ast.Load):
            return ast.copy_location(_name(self.new), node)
        return node


def _assigned_names(stmts):
    """Names (re)bound anywhere in these statements, not descending into
    nested function/class definitions."""
    names = []

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            if node.id not in names:
                names.append(node.id)
        for child in ast.iter_child_nodes(node):
            visit(child)

    for s in stmts:
        visit(s)
    return names


def _transformable(stmts):
    # statements this transformer itself generated (UNDEF preambles,
    # branch helper defs, _jst calls) are always acceptable — without
    # this, an already-rewritten inner `elif` blocks the outer `if`
    return all(isinstance(s, _SIMPLE_STMTS)
               or getattr(s, "_dy2s_generated", False) for s in stmts)


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name,
                         ctx=ast.Load())


def _undef_preamble(var):
    """try: v \n except NameError/UnboundLocalError: v = _jst.UNDEF"""
    return ast.Try(
        body=[ast.Expr(value=_name(var))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[_name("NameError"),
                                 _name("UnboundLocalError")],
                           ctx=ast.Load()),
            name=None,
            body=[ast.Assign(targets=[_name(var, ast.Store())],
                             value=_jst_attr("UNDEF"))])],
        orelse=[], finalbody=[])


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[_name(n) for n in names], ctx=ast.Load()))


def _assign_tuple(names, value):
    return ast.Assign(
        targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                           ctx=ast.Store())],
        value=value)


def _contains_return_deep(stmts):
    """True if a `return` appears ANYWHERE under these statements,
    descending through loops (unlike _contains_ctrl) but not into nested
    function/class definitions."""
    stop = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)

    def visit(node):
        if isinstance(node, stop):
            return False
        if isinstance(node, ast.Return):
            return True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, stop):
                continue
            if visit(child):
                return True
        return False

    return any(visit(s) for s in stmts)


def _contains_ctrl(stmts, kinds):
    """True if any node of `kinds` appears at THIS loop/function level
    (not inside nested loops or function defs, whose break/continue
    belong to them)."""
    stop = (ast.For, ast.While, ast.AsyncFor, ast.FunctionDef,
            ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)

    def visit(node, top=False):
        if not top and isinstance(node, stop):
            return False
        if isinstance(node, kinds):
            return True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, stop):
                continue
            if visit(child):
                return True
        return False

    # the top-level statements themselves are searched even when they
    # are loops (callers pass e.g. [the_loop_node] deliberately)
    return any(visit(s, top=True) for s in stmts)


def _bool_const(v):
    return ast.Constant(value=v)


def _rewrite_break_continue(body, uid):
    """Flag rewriting for mid-body break/continue (parity:
    dygraph_to_static/break_continue_transformer.py — re-designed for the
    lax lowering). Returns (pre_stmts, new_body, brk_name or None).

    `break` -> `__dy2s_brk = True`; `continue` -> `__dy2s_cnt = True`;
    every statement after a possible flag set is guarded with
    `if not (brk or cnt):` (a plain if, which the control-flow
    transformer then lowers to lax.cond when traced). The continue flag
    resets each iteration; the break flag persists in the loop carry and
    the caller folds `and not brk` into the loop condition."""
    if not _contains_ctrl(body, (ast.Break, ast.Continue)):
        return [], body, None
    brk = f"__dy2s_brk_{uid}"
    cnt = f"__dy2s_cnt_{uid}"

    def guard_test():
        return ast.Call(
            func=_jst_attr("logical_not"),
            args=[ast.Call(func=_jst_attr("logical_or"),
                           args=[_name(brk), _name(cnt)], keywords=[])],
            keywords=[])

    def set_flag(name):
        return ast.Assign(targets=[_name(name, ast.Store())],
                          value=_bool_const(True))

    def rewrite_stmt(st):
        """-> (new_stmt, may_set_flag)"""
        if isinstance(st, ast.Break):
            return set_flag(brk), True
        if isinstance(st, ast.Continue):
            return set_flag(cnt), True
        if isinstance(st, ast.If) and _contains_ctrl(
                [st], (ast.Break, ast.Continue)):
            b2, s1 = rewrite_seq(st.body)
            o2, s2 = rewrite_seq(st.orelse)
            return ast.If(test=st.test, body=b2,
                          orelse=o2), (s1 or s2)
        return st, False

    def rewrite_seq(stmts):
        out, sets_any, guarded = [], False, False
        for st in stmts:
            st2, sets = rewrite_stmt(st)
            if guarded:
                out.append(ast.If(test=guard_test(), body=[st2],
                                  orelse=[]))
            else:
                out.append(st2)
            if sets:
                sets_any = True
                guarded = True
        return out, sets_any

    new_body, _ = rewrite_seq(body)
    # continue resets every iteration; break persists across iterations
    new_body = [ast.Assign(targets=[_name(cnt, ast.Store())],
                           value=_bool_const(False))] + new_body
    # both flags pre-initialised: they ride the loop carry
    pre = [ast.Assign(targets=[_name(brk, ast.Store())],
                      value=_bool_const(False)),
           ast.Assign(targets=[_name(cnt, ast.Store())],
                      value=_bool_const(False))]
    return pre, new_body, brk


class _UnsupportedReturn(Exception):
    pass


def _rewrite_returns(body, retv):
    """Single-exit rewriting for return-inside-branch (parity:
    dygraph_to_static/return_transformer.py — re-designed as else-hoisting
    instead of guard flags, which lowers cleanly to lax.cond).

    Returns (new_stmts, always_returns). `return X` becomes
    `retv = X`; when an if-branch always returns, the statements after
    the `if` are hoisted into its else side, so control flow stays
    structured and every path ends assigning `retv`. Returns inside
    loops (or partially-returning branches) raise _UnsupportedReturn —
    the caller leaves the function untransformed (eager fallback)."""

    import copy

    # continuation duplication doubles the spliced tail per returning
    # `if`; cap total emitted statements so a long guard-clause chain
    # falls back to eager instead of exploding (O(2^k))
    budget = [2000]

    def spend(stmts):
        budget[0] -= len(stmts)
        if budget[0] < 0:
            raise _UnsupportedReturn("return-rewrite size budget")

    def block(stmts):
        if not stmts:
            return [], False
        st, rest = stmts[0], list(stmts[1:])
        if isinstance(st, ast.Return):
            return [ast.Assign(
                targets=[_name(retv, ast.Store())],
                value=st.value if st.value is not None
                else ast.Constant(value=None))], True  # rest unreachable
        if isinstance(st, (ast.For, ast.While)) and _contains_ctrl(
                [st], (ast.Return,)):
            raise _UnsupportedReturn("return inside loop")
        if isinstance(st, ast.If) and _contains_ctrl(
                [st], (ast.Return,)):
            # continuation duplication: whatever follows the `if` runs
            # on any branch path that falls through, so splice `rest`
            # into BOTH branch continuations (deep-copied on one side —
            # shared AST subtrees confuse location fixing)
            spend(rest)  # each duplicating `if` spends its tail once
            tb, ta = block(list(st.body) + copy.deepcopy(rest))
            fb, fa = block(list(st.orelse) + rest)
            return [ast.If(test=st.test, body=tb or [ast.Pass()],
                           orelse=fb or [ast.Pass()])], ta and fa
        out, always = block(rest)
        return [st] + out, always

    return block(body)


def _hoist_loop_returns(body):
    """Return-inside-loop rewriting (parity:
    dygraph_to_static/return_transformer.py's loop handling). A shared
    (flag, value) pair turns `return e` inside any loop into
    `flag = True; val = e; break`; every loop that transitively
    contained a return is followed by `if flag: break` (when itself
    nested in a loop) or `if flag: return val` (at function level),
    which the subsequent single-exit pass else-hoists. Returns
    (new_body, used).

    Traced-loop contract: `val` is pre-initialised to 0.0 so it can ride
    a lax carry; loops returning non-f32-scalar values under tracing
    fail the carry check loudly and fall back to eager (documented)."""
    FLAG, VAL = "__dy2s_rflag", "__dy2s_rval"
    used = [False]

    def assign(name, value):
        return ast.Assign(targets=[_name(name, ast.Store())], value=value)

    def rewrite(stmts, in_loop):
        out = []
        for st in stmts:
            if isinstance(st, ast.Return) and in_loop:
                used[0] = True
                out.append(assign(FLAG, ast.Constant(value=True)))
                out.append(assign(VAL, st.value if st.value is not None
                                  else ast.Constant(value=None)))
                out.append(ast.Break())
                continue
            if isinstance(st, (ast.For, ast.While)) and \
                    _contains_return_deep([st]):
                st.body = rewrite(st.body, True)
                if st.orelse:
                    st.orelse = rewrite(st.orelse, in_loop)
                out.append(st)
                if in_loop:
                    out.append(ast.If(test=_name(FLAG),
                                      body=[ast.Break()], orelse=[]))
                else:
                    out.append(ast.If(
                        test=_name(FLAG),
                        body=[ast.Return(value=_name(VAL))], orelse=[]))
                continue
            if isinstance(st, ast.If):
                st.body = rewrite(st.body, in_loop)
                st.orelse = rewrite(st.orelse, in_loop)
                out.append(st)
                continue
            out.append(st)
        return out

    new = rewrite(list(body), False)
    if used[0]:
        new = [assign(FLAG, ast.Constant(value=False)),
               assign(VAL, ast.Constant(value=0.0))] + new
    return new, used[0]


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._counter = 0

    def _uid(self):
        self._counter += 1
        return self._counter

    # -- don't descend into nested defs/lambdas: they run as plain python
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if not (_transformable(node.body)
                and _transformable(node.orelse or [ast.Pass()])):
            return node
        if _contains_return_deep(node.body + node.orelse):
            # a `return` anywhere under this if (e.g. inside a nested
            # python-fallback loop) must keep python early-exit
            # semantics — lowering to cond would swallow it into the
            # branch tuple
            return node
        body_names = _assigned_names(node.body)
        else_names = _assigned_names(node.orelse)
        outs = _assigned_names(node.body + node.orelse)
        if not outs:
            return node
        both_flags = tuple(n in body_names and n in else_names
                           for n in outs)
        uid = self._uid()
        tname, fname = f"__dy2s_true_{uid}", f"__dy2s_false_{uid}"
        # outer values captured via default args so aug-assigns/reads of
        # the output vars resolve inside the generated functions
        arg_defaults = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in outs],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[_name(n) for n in outs])
        tdef = ast.FunctionDef(
            name=tname, args=arg_defaults,
            body=list(node.body) + [_ret_tuple(outs)],
            decorator_list=[], returns=None)
        fdef = ast.FunctionDef(
            name=fname, args=arg_defaults,
            body=list(node.orelse or [ast.Pass()]) + [_ret_tuple(outs)],
            decorator_list=[], returns=None)
        call = ast.Call(
            func=_jst_attr("cond"),
            args=[node.test, _name(tname), _name(fname),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in outs],
                            ctx=ast.Load()),
                  # current values + static both-branch-assigned flags:
                  # lets cond() find undefined slots without probing
                  ast.Tuple(elts=[_name(n) for n in outs],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=b)
                                  for b in both_flags],
                            ctx=ast.Load())],
            keywords=[])
        stmts = [_undef_preamble(n) for n in outs]
        stmts += [tdef, fdef, _assign_tuple(outs, call)]
        return _mark_generated(stmts)

    def _loop_helpers(self, loop_vars, body_stmts, test_expr, uid,
                      trips_expr=None):
        cname, bname = f"__dy2s_cond_{uid}", f"__dy2s_body_{uid}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in loop_vars],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        cdef = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=test_expr)],
            decorator_list=[], returns=None)
        bdef = ast.FunctionDef(
            name=bname, args=args,
            body=body_stmts + [_ret_tuple(loop_vars)],
            decorator_list=[], returns=None)
        vars_tuple = ast.Tuple(elts=[_name(n) for n in loop_vars],
                               ctx=ast.Load())
        if trips_expr is not None:
            call = ast.Call(
                func=_jst_attr("bounded_while"),
                args=[_name(cname), _name(bname), vars_tuple,
                      trips_expr],
                keywords=[])
        else:
            call = ast.Call(
                func=_jst_attr("while_loop"),
                args=[_name(cname), _name(bname), vars_tuple],
                keywords=[])
        return [cdef, bdef, _assign_tuple(loop_vars, call)]

    @staticmethod
    def _fold_leading_break(body, test):
        """`while c: if b: break; rest` == `while c and not b: rest`."""
        if body and isinstance(body[0], ast.If) and not body[0].orelse \
                and len(body[0].body) == 1 \
                and isinstance(body[0].body[0], ast.Break):
            # python `and`/`not` would force bool() on tracers — use the
            # tracer-aware logical helpers
            folded = ast.Call(
                func=_jst_attr("logical_and"),
                args=[test,
                      ast.Call(func=_jst_attr("logical_not"),
                               args=[body[0].test], keywords=[])],
                keywords=[])
            return body[1:], folded
        return body, test

    def _augment_break(self, test, brk):
        return ast.Call(
            func=_jst_attr("logical_and"),
            args=[test, ast.Call(func=_jst_attr("logical_not"),
                                 args=[_name(brk)], keywords=[])],
            keywords=[])

    def _bail_loop(self, orig):
        """Fallback for a loop we decided not to transform: the ORIGINAL
        node (no flag rewriting / test augmentation baked in), with its
        nested constructs still visited."""
        self.generic_visit(orig)
        return orig

    def visit_While(self, node):
        if node.orelse:
            self.generic_visit(node)
            return node
        if _contains_ctrl(node.body, (ast.Return,)):
            # a return that escapes the loop can't ride the lax carry —
            # leave the whole loop to python (eager fallback)
            self.generic_visit(node)
            return node
        import copy
        orig = copy.deepcopy(node)
        uid = self._uid()
        body0, test = self._fold_leading_break(node.body, node.test)
        pre, body0, brk = _rewrite_break_continue(body0, uid)
        if brk is not None:
            test = self._augment_break(test, brk)
        node.body = body0
        node.test = test
        self.generic_visit(node)
        body = node.body
        if not _transformable(body):
            return self._bail_loop(orig)
        loop_vars = _assigned_names(body)
        if not loop_vars:
            return self._bail_loop(orig)
        stmts = list(pre)
        stmts += [_undef_preamble(n) for n in loop_vars
                  if not any(isinstance(p, ast.Assign)
                             and p.targets[0].id == n for p in pre)]
        stmts += self._loop_helpers(loop_vars, body, node.test, uid)
        return _mark_generated(stmts)

    def visit_For(self, node):
        if node.orelse or not isinstance(node.target, ast.Name):
            self.generic_visit(node)
            return node
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords):
            # non-range iterable (for x in seq): rewrite to an indexed
            # range loop over _jst.seq_len(seq) — tensors iterate dim-0
            # slices (traced indices ok); python sequences only reach
            # the eager path (concrete indices). enumerate/zip/dict
            # targets are tuple-unpacking and bail above.
            uid = self._uid()
            seq = f"__dy2s_seq_{uid}"
            idx = f"__dy2s_it_{uid}"
            seq_assign = ast.Assign(
                targets=[_name(seq, ast.Store())],
                value=ast.Call(func=_jst_attr("as_seq"), args=[it],
                               keywords=[]))
            get = ast.Assign(
                targets=[node.target],
                value=ast.Call(func=_jst_attr("seq_get"),
                               args=[_name(seq), _name(idx)],
                               keywords=[]))
            rng = ast.Call(
                func=_name("range"),
                args=[ast.Call(func=_jst_attr("seq_len"),
                               args=[_name(seq)], keywords=[])],
                keywords=[])
            new_for = ast.For(target=_name(idx, ast.Store()), iter=rng,
                              body=[get] + node.body, orelse=[])
            out = self.visit(new_for)
            return _mark_generated(
                [seq_assign] + (out if isinstance(out, list) else [out]))
        if _contains_ctrl(node.body, (ast.Return,)):
            self.generic_visit(node)
            return node
        import copy
        orig = copy.deepcopy(node)
        uid = self._uid()
        i = node.target.id
        # internal counter `ctr` drives the loop; the USER's variable is
        # assigned from it at body start, so after the loop it holds the
        # last ITERATED value (python for semantics), not one past it
        ctr = f"__dy2s_i_{uid}"
        stop_v, step_v = f"__dy2s_stop_{uid}", f"__dy2s_step_{uid}"
        start_assign = _assign_tuple(
            [ctr, stop_v, step_v],
            ast.Call(func=_jst_attr("range3"), args=list(it.args),
                     keywords=[]))
        test = ast.Call(func=_jst_attr("range_cond"),
                        args=[_name(ctr), _name(stop_v), _name(step_v)],
                        keywords=[])
        body, test = self._fold_leading_break(node.body, test)
        # the folded break test runs in the loop CONDITION, where the
        # user's variable still holds the previous iteration's value —
        # the internal counter is the current one, so reads of the loop
        # var inside the folded test must use the counter
        test = _RenameVar(i, ctr).visit(test)
        pre, body, brk = _rewrite_break_continue(body, uid)
        if brk is not None:
            test = self._augment_break(test, brk)
        node.body = body
        self.generic_visit(node)
        body = node.body
        if not _transformable(body):
            return self._bail_loop(orig)
        set_user = ast.Assign(targets=[_name(i, ast.Store())],
                              value=_name(ctr))
        # the counter increment sits after the (possibly guarded) body:
        # `continue` still advances it, and the user's `i` (assigned at
        # body start) keeps the breaking iteration's value on `break`
        incr = ast.AugAssign(target=_name(ctr, ast.Store()),
                             op=ast.Add(), value=_name(step_v))
        body = [set_user] + body + [incr]
        loop_vars = [ctr, i] + [n for n in _assigned_names(body)
                                if n not in (ctr, i)]
        pre_names = {p.targets[0].id for p in pre
                     if isinstance(p, ast.Assign)}
        stmts = [start_assign,
                 # seed the user's var so the traced carry is defined even
                 # for range(0) (python would NameError on a later read;
                 # we leave it at start — documented approximation)
                 ast.Assign(targets=[_name(i, ast.Store())],
                            value=_name(ctr))] + list(pre)
        stmts += [_undef_preamble(n) for n in loop_vars
                  if n not in (ctr, i) and n not in pre_names]
        # static-bound range loops lower to a masked lax.scan
        # (differentiable); dynamic bounds fall back to lax.while_loop
        trips = ast.Call(func=_jst_attr("trip_count"),
                         args=[_name(ctr), _name(stop_v), _name(step_v)],
                         keywords=[])
        stmts += self._loop_helpers(loop_vars, body, test, uid,
                                    trips_expr=trips)
        return _mark_generated(stmts)


_cache = {}


def transform_function(fn):
    """Rewrite data-dependent control flow in `fn` (a function or bound
    method) into _jst.cond/while_loop calls. Returns the original on any
    failure (source unavailable, unsupported constructs, …)."""
    if isinstance(fn, types.MethodType):
        new = transform_function(fn.__func__)
        return types.MethodType(new, fn.__self__)
    if fn in _cache:
        return _cache[fn]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            raise ValueError("not a function definition")
        fdef.decorator_list = []
        # pass 0: return-inside-loop -> shared flag + break + guarded
        # return (then pass 1 else-hoists the guard)
        did_loop_ret = False
        if any(isinstance(s, (ast.For, ast.While))
               and _contains_return_deep([s]) for s in ast.walk(fdef)):
            fdef.body, did_loop_ret = _hoist_loop_returns(fdef.body)
        # pass 1: single-exit return rewriting (return-inside-branch)
        did_return_rewrite = did_loop_ret
        body0 = fdef.body
        top_last_ret = body0 and isinstance(body0[-1], ast.Return)
        early = body0[:-1] if top_last_ret else body0
        if _contains_ctrl(early, (ast.Return,)) or any(
                isinstance(s, (ast.For, ast.While))
                and _contains_ctrl([s], (ast.Return,)) for s in early):
            retv = "__dy2s_ret"
            try:
                new0, always = _rewrite_returns(body0, retv)
                pre0 = [] if always else [ast.Assign(
                    targets=[_name(retv, ast.Store())],
                    value=ast.Constant(value=None))]
                fdef.body = pre0 + new0 + [
                    ast.Return(value=_name(retv))]
                did_return_rewrite = True
            except _UnsupportedReturn:
                pass  # leave returns as-is (eager fallback semantics)
        # pass 2: control flow -> _jst.cond / while_loop
        new_body = []
        tr = _ControlFlowTransformer()
        for stmt in fdef.body:
            out = tr.visit(stmt)
            new_body.extend(out if isinstance(out, list) else [out])
        if tr._counter == 0 and not did_return_rewrite:
            _cache[fn] = fn  # nothing to rewrite
            return fn
        fdef.body = new_body
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        glb = dict(fn.__globals__)
        # re-expose the original closure as globals (exec'd functions
        # have no closure cells)
        if fn.__closure__:
            for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
                try:
                    glb[name] = cell.cell_contents
                except ValueError:
                    pass
        import paddle_tpu.jit.dy2static as _jst_mod
        glb["_jst"] = _jst_mod
        loc = {}
        exec(code, glb, loc)
        new_fn = loc[fdef.name]
        new_fn = functools.wraps(fn)(new_fn)
        _cache[fn] = new_fn
        return new_fn
    except Exception:
        _cache[fn] = fn
        return fn
