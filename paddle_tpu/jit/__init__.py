"""paddle_tpu.jit — dygraph-to-compiled bridge.

Parity: `python/paddle/fluid/dygraph/jit.py` (`to_static`, `jit.save/load`)
and the dy2static stack (`dygraph_to_static/program_translator.py:1001`).
TPU-native: `to_static` wraps forward in a functional `jax.jit` (XLA is the
Program+Executor); `save` exports state_dict + StableHLO text when possible.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from ..core.tensor import Tensor
from ..core import autograd
from ..core import random as rng_mod
from . import functional
from .functional import bind_arrays, split_state
from .trainer import CompiledTrainStep, CompiledEvalStep  # noqa: F401
from . import dy2static  # noqa: F401

_to_static_enabled = [True]


def enable_to_static(flag: bool):
    """ProgramTranslator().enable() parity: globally toggle the dy2static
    AST rewrite inside to_static."""
    _to_static_enabled[0] = bool(flag)


class StaticFunction:
    """Compiled callable wrapping a Layer's forward or a plain function."""

    def __init__(self, function, input_spec=None):
        from ..nn.layer_base import Layer
        self._layer = None
        if isinstance(function, Layer):
            self._layer = function
            self._fn = function.forward
        else:
            self._fn = function
            self._layer = getattr(function, "__self__", None)
        self.input_spec = input_spec
        self._compiled = None

    def _build(self):
        layer = self._layer
        fn = self._fn
        if _to_static_enabled[0]:
            # AST-rewrite data-dependent python control flow into
            # lax.cond/while_loop calls (dy2static transformer parity);
            # returns fn unchanged when there is nothing to rewrite or
            # the source is unavailable
            fn = dy2static.transform_function(fn)
        if layer is not None:
            p_names, p_tensors, b_names, b_tensors = split_state(layer)

            def run(params, buffers, key, *arrays):
                wrapped = [Tensor(a) for a in arrays]
                with bind_arrays(p_tensors, params), \
                        bind_arrays(b_tensors, buffers), \
                        rng_mod.functional_rng(key), autograd.no_grad():
                    out = fn(*wrapped)
                outs = out if isinstance(out, (list, tuple)) else [out]
                return [o._data if isinstance(o, Tensor) else o
                        for o in outs], not isinstance(out, (list, tuple))
            jit_run = functional.instrumented_jit(
                run, f"to_static/{type(layer).__name__}",
                static_argnums=())
            self._p_tensors, self._b_tensors = p_tensors, b_tensors

            def call(*args):
                arrays = [a._data if isinstance(a, Tensor)
                          else np.asarray(a) for a in args]
                outs, single = jit_run(
                    [p._data for p in p_tensors],
                    [b._data for b in b_tensors],
                    rng_mod.next_key(), *arrays)
                outs = [Tensor(o) for o in outs]
                return outs[0] if single else outs
            return call

        def run(key, *arrays):
            wrapped = [Tensor(a) for a in arrays]
            with rng_mod.functional_rng(key), autograd.no_grad():
                out = fn(*wrapped)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o._data if isinstance(o, Tensor) else o
                    for o in outs], not isinstance(out, (list, tuple))
        jit_run = functional.instrumented_jit(
            run, f"to_static/{getattr(self._fn, '__name__', 'fn')}")

        def call(*args):
            arrays = [a._data if isinstance(a, Tensor) else np.asarray(a)
                      for a in args]
            outs, single = jit_run(rng_mod.next_key(), *arrays)
            outs = [Tensor(o) for o in outs]
            return outs[0] if single else outs
        return call

    def __call__(self, *args, **kwargs):
        if self._compiled is None:
            self._compiled = self._build()
        return self._compiled(*args)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None):
    """@paddle.jit.to_static parity."""
    def decorate(fn):
        from ..nn.layer_base import Layer
        if isinstance(fn, Layer):
            sf = StaticFunction(fn, input_spec)
            orig_forward = fn.forward
            fn.forward = sf  # layer(x) now runs compiled
            fn._orig_forward = orig_forward
            return fn
        return StaticFunction(fn, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def save(layer, path, input_spec=None, **configs):
    """jit.save: state_dict + (best-effort) StableHLO export.

    Format parity target: the reference saves program+params
    (`fluid/dygraph/jit.py`, `paddle/fluid/jit/serializer.cc`); we save
    pickled state_dict + an exported StableHLO module when input_spec is
    given (the AOT serving artifact — AnalysisPredictor capability)."""
    from ..nn.layer_base import Layer
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    net = layer
    state = {k: np.asarray(v.numpy())
             for k, v in net.state_dict().items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {"class": type(net).__name__, "input_spec": None}
    if input_spec:
        try:
            import jax.export as jexport
            from ..core import dtype as dtype_mod
            p_names, p_tensors, b_names, b_tensors = split_state(net)

            n_p = len(p_tensors)

            def fwd(state_list, *xs):
                wrapped = [Tensor(a) for a in xs]
                with bind_arrays(p_tensors, state_list[:n_p]), \
                        bind_arrays(b_tensors, state_list[n_p:]), \
                        autograd.no_grad():
                    out = net(*wrapped)
                outs = out if isinstance(out, (list, tuple)) else [out]
                return [o._data for o in outs]
            import jax.numpy as jnp
            sample = [
                jnp.zeros([d if d and d > 0 else 1 for d in spec.shape],
                          dtype_mod.convert_dtype(spec.dtype))
                for spec in input_spec]
            exported = jexport.export(jax.jit(fwd))(
                [p._data for p in p_tensors]
                + [b._data for b in b_tensors], *sample)
            meta["state_order"] = p_names + b_names
            with open(path + ".stablehlo", "wb") as f:
                f.write(exported.serialize())
            meta["input_spec"] = [(list(s.shape), str(s.dtype))
                                  for s in input_spec]
        except Exception as e:
            meta["export_error"] = str(e)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """jit.load result: runs the exported StableHLO module."""

    def __init__(self, path):
        with open(path + ".pdparams", "rb") as f:
            self.state = pickle.load(f)
        with open(path + ".pdmodel", "rb") as f:
            self.meta = pickle.load(f)
        self._exported = None
        hlo = path + ".stablehlo"
        if os.path.exists(hlo):
            import jax.export as jexport
            with open(hlo, "rb") as f:
                self._exported = jexport.deserialize(f.read())

    def __call__(self, *args):
        if self._exported is None:
            raise RuntimeError("no compiled module was exported at save "
                               "time; re-save with input_spec")
        arrays = [a._data if isinstance(a, Tensor) else np.asarray(a)
                  for a in args]
        state_list = [self.state[k] for k in self.meta["state_order"]]
        out = self._exported.call(state_list, *arrays)
        return [Tensor(o) for o in out]

    def state_dict(self):
        return self.state


def load(path, **configs):
    return TranslatedLayer(path)


def not_to_static(fn=None):
    return fn


# ------------------------------------------------------- control flow
# Parity: the dy2static control-flow transformers
# (`fluid/dygraph/dygraph_to_static/ast_transformer.py` ifelse/loop) and
# static `paddle.static.nn.cond/while_loop` ops. Under tracing these map
# straight to lax.cond / lax.while_loop; eagerly they just execute.


def cond(pred, true_fn, false_fn, *operands):
    import jax
    from ..core.tensor import Tensor
    p = pred._data if isinstance(pred, Tensor) else pred

    def _wrap(fn):
        def inner(ops_):
            out = fn(*[Tensor(o) for o in ops_]) if ops_ else fn()
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o._data if isinstance(o, Tensor) else o for o in outs]
        return inner
    ops_ = [o._data if isinstance(o, Tensor) else o for o in operands]
    res = jax.lax.cond(p, _wrap(true_fn), _wrap(false_fn), ops_)
    res = [Tensor(r) for r in res]
    return res[0] if len(res) == 1 else res


def while_loop(cond_fn, body_fn, loop_vars):
    import jax
    from ..core.tensor import Tensor
    init = [v._data if isinstance(v, Tensor) else v for v in loop_vars]

    def c(vs):
        out = cond_fn(*[Tensor(v) for v in vs])
        return out._data if isinstance(out, Tensor) else out

    def b(vs):
        out = body_fn(*[Tensor(v) for v in vs])
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o._data if isinstance(o, Tensor) else o for o in outs]
    res = jax.lax.while_loop(c, b, init)
    return [Tensor(r) for r in res]
