"""Compiled whole-step trainer: forward + backward + optimizer update in ONE
donated `jax.jit` executable.

This subsumes the reference's static-graph executor stack for training
(SURVEY.md §3.3: `StandaloneExecutor` → `InterpreterCore` instruction
stream): XLA's scheduler replaces stream_analyzer/workqueues, buffer
donation replaces the memory_optimize/inplace passes, and the fused
optimizer update replaces `coalesce_grad_tensor_pass` + merged_adam.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core import random as rng_mod
from ..core.tensor import Tensor
from .functional import bind_arrays, split_state
from ..optimizer.optimizer import _clip_spec


class CompiledTrainStep:
    """train_step(params, buffers, accums, lr, t, key, *batch) compiled once
    per input-shape signature."""

    def __init__(self, model, loss_fn, optimizer, n_labels=1,
                 donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.n_labels = n_labels
        (self.p_names, self.p_tensors,
         self.b_names, self.b_tensors) = split_state(model)
        # ensure accumulators exist
        self.accum_template = [optimizer._get_accums(p)
                               for p in self.p_tensors]
        clip_kind, clip_value = _clip_spec(optimizer._grad_clip)
        single = optimizer._single_update
        from ..optimizer.optimizer import _wd_coeff
        wds = tuple(
            p.optimize_attr.get("weight_decay", optimizer._weight_decay)
            if p.regularizer is None else _wd_coeff(p.regularizer)
            for p in self.p_tensors)
        lr_mults = tuple(p.optimize_attr.get("learning_rate", 1.0)
                         for p in self.p_tensors)
        trainable = tuple(not p.stop_gradient for p in self.p_tensors)
        model_ref = model
        loss_ref = loss_fn
        p_tensors = self.p_tensors
        b_tensors = self.b_tensors
        n_lab = n_labels

        def step(params, buffers, accums, lr, t, key, *batch):
            inputs, labels = batch[:len(batch) - n_lab], \
                batch[len(batch) - n_lab:]

            def loss_of(plist):
                wrapped_in = [Tensor(a) for a in inputs]
                wrapped_lab = [Tensor(a) for a in labels]
                with bind_arrays(p_tensors, plist), \
                        bind_arrays(b_tensors, buffers), \
                        rng_mod.functional_rng(key), autograd.no_grad():
                    out = model_ref(*wrapped_in)
                    outs = out if isinstance(out, (list, tuple)) else [out]
                    if loss_ref is not None:
                        loss = loss_ref(*outs, *wrapped_lab)
                    else:
                        loss = outs[0]
                    new_buf = [b._data for b in b_tensors]
                loss_arr = loss._data if isinstance(loss, Tensor) else loss
                out_arrs = [o._data if isinstance(o, Tensor) else o
                            for o in outs]
                return loss_arr.astype(jnp.float32), (new_buf, out_arrs)

            (loss, (new_buffers, outs)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(params))

            # grad clip (global-norm inside the compiled step)
            if clip_kind == "global_norm":
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g, tr in zip(grads, trainable) if tr) + 1e-12)
                scale = jnp.minimum(1.0, clip_value / (gnorm + 1e-6))
                grads = [g * scale.astype(g.dtype) for g in grads]
            elif clip_kind == "value":
                grads = [jnp.clip(g, -clip_value, clip_value) for g in grads]

            new_params, new_accums = [], []
            for p, g, acc, wd, lm, tr in zip(params, grads, accums, wds,
                                             lr_mults, trainable):
                if not tr:
                    new_params.append(p)
                    new_accums.append(acc)
                    continue
                np_, nacc = single(p, g, acc, lr * lm, t, wd)
                new_params.append(np_)
                new_accums.append(nacc)
            return loss, outs, new_params, new_buffers, new_accums

        donate_argnums = (0, 2) if donate else ()
        self._jit_step = jax.jit(step, donate_argnums=donate_argnums)

    def run(self, *batch_arrays):
        opt = self.optimizer
        params = [p._data for p in self.p_tensors]
        buffers = [b._data for b in self.b_tensors]
        accums = [opt._accumulators[id(p)] for p in self.p_tensors]
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        t = jnp.asarray(opt._step_count + 1, jnp.float32)
        key = rng_mod.next_key()
        loss, outs, new_params, new_buffers, new_accums = self._jit_step(
            params, buffers, accums, lr, t, key, *batch_arrays)
        for p, np_ in zip(self.p_tensors, new_params):
            p._data = np_
        for b, nb in zip(self.b_tensors, new_buffers):
            b._data = nb
        for p, nacc in zip(self.p_tensors, new_accums):
            opt._accumulators[id(p)] = nacc
        opt._step_count += 1
        return Tensor(loss), [Tensor(o) for o in outs]


class CompiledEvalStep:
    def __init__(self, model, loss_fn=None, n_labels=1):
        self.model = model
        (self.p_names, self.p_tensors,
         self.b_names, self.b_tensors) = split_state(model)
        model_ref = model
        loss_ref = loss_fn
        p_tensors, b_tensors = self.p_tensors, self.b_tensors
        n_lab = n_labels

        def step(params, buffers, key, *batch):
            inputs = batch[:len(batch) - n_lab] if loss_ref is not None \
                else batch
            labels = batch[len(batch) - n_lab:] if loss_ref is not None \
                else ()
            wrapped_in = [Tensor(a) for a in inputs]
            wrapped_lab = [Tensor(a) for a in labels]
            with bind_arrays(p_tensors, params), \
                    bind_arrays(b_tensors, buffers), \
                    rng_mod.functional_rng(key), autograd.no_grad():
                out = model_ref(*wrapped_in)
                outs = out if isinstance(out, (list, tuple)) else [out]
                loss_arr = None
                if loss_ref is not None:
                    loss = loss_ref(*outs, *wrapped_lab)
                    loss_arr = loss._data if isinstance(loss, Tensor) \
                        else loss
            out_arrs = [o._data if isinstance(o, Tensor) else o
                        for o in outs]
            return loss_arr, out_arrs

        self._jit_step = jax.jit(step)

    def run(self, *batch_arrays):
        params = [p._data for p in self.p_tensors]
        buffers = [b._data for b in self.b_tensors]
        key = rng_mod.next_key()
        loss, outs = self._jit_step(params, buffers, key, *batch_arrays)
        return (Tensor(loss) if loss is not None else None,
                [Tensor(o) for o in outs])
