"""Functionalisation: run a stateful Layer as a pure jax function.

This is the TPU-native replacement for the reference's dygraph-to-static
bridge (`python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py`
+ `partial_program.py`): instead of AST-transforming python into a static
Program run by InterpreterCore, we temporarily bind traced arrays into the
layer's Parameters/buffers and trace the ordinary eager forward under
`jax.jit` — XLA is the static executor (SURVEY.md §7.5).
"""
from __future__ import annotations

import contextlib
import functools
import time

import jax
import jax.numpy as jnp

from ..core import autograd
from ..core import random as rng_mod
from ..core.tensor import Tensor
from ..profiler import metrics as _metrics


def instrumented_jit(fn, name, **jit_kwargs):
    """`jax.jit` with compile accounting: when profiler metrics are
    enabled, calls that trigger a fresh trace+compile (detected via the
    jitted callable's compilation-cache size) increment
    paddle_tpu_jit_compiles_total{fn=name} and add their wall time to
    paddle_tpu_jit_compile_seconds_total{fn=name}. When an
    `analysis.guards` sanitize scope is active, fresh compiles are also
    reported to its compile-count watchdog keyed by (name, THIS
    wrapper) — so per-instance one-compile budgets hold even with
    metrics off. Neither active, the wrapper is one branch over the
    plain jitted call."""
    from ..analysis import guards as _guards
    jitted = jax.jit(fn, **jit_kwargs)
    cache_size = getattr(jitted, "_cache_size", None)
    instance = _guards.next_instance_id()

    @functools.wraps(fn)
    def call(*args, **kwargs):
        timed = _metrics._enabled
        if (not timed and not _guards.active()) or cache_size is None:
            return jitted(*args, **kwargs)
        try:
            before = cache_size()
        except Exception:
            return jitted(*args, **kwargs)
        # watchdog-only tracking (metrics off) skips the clock reads:
        # two cache-size probes per call is its whole per-step cost
        t0 = time.perf_counter() if timed else 0.0
        out = jitted(*args, **kwargs)
        dt = time.perf_counter() - t0 if timed else 0.0
        try:
            compiled = cache_size() - before
        except Exception:
            compiled = 0
        if compiled > 0:
            if timed:
                _metrics.JIT_COMPILES.labels(name).inc(compiled)
                # dt spans trace+compile+first execution — the honest
                # cost of hitting an uncompiled signature
                _metrics.JIT_COMPILE_SECONDS.labels(name).inc(dt)
            _guards.notify_compile(name, instance, compiled)
        return out

    call._jitted = jitted
    call._watchdog_instance = instance
    return call


@contextlib.contextmanager
def bind_arrays(tensors, arrays):
    old = [t._data for t in tensors]
    for t, a in zip(tensors, arrays):
        t._data = a
    try:
        yield
    finally:
        for t, o in zip(tensors, old):
            t._data = o


def split_state(layer):
    """(param_names, param_tensors, buffer_names, buffer_tensors)."""
    p_names, p_tensors = [], []
    for n, p in layer.named_parameters():
        p_names.append(n)
        p_tensors.append(p)
    b_names, b_tensors = [], []
    for n, b in layer.named_buffers():
        b_names.append(n)
        b_tensors.append(b)
    return p_names, p_tensors, b_names, b_tensors


def call_functional(layer, param_tensors, buffer_tensors, param_arrays,
                    buffer_arrays, args, rng_key, grad_params=True):
    """Run layer(*args) with the given arrays bound in; returns
    (outputs_arrays, new_buffer_arrays). Tape is disabled — gradients come
    from jax AD over this function."""
    wrapped = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
    with bind_arrays(param_tensors, param_arrays), \
            bind_arrays(buffer_tensors, buffer_arrays), \
            rng_mod.functional_rng(rng_key), autograd.no_grad():
        out = layer(*wrapped)
        new_buffers = [b._data for b in buffer_tensors]
    return out, new_buffers


def tree_arrays(x):
    """Extract raw arrays from Tensor/list/tuple/dict structures."""
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(tree_arrays(v) for v in x)
    if isinstance(x, dict):
        return {k: tree_arrays(v) for k, v in x.items()}
    return x
