"""paddle_tpu.text — `python/paddle/text/` parity essentials.

Datasets are zero-egress synthetic stand-ins (same API shapes); the real
op here is viterbi_decode (`paddle.text.viterbi_decode`,
`paddle/phi/kernels/viterbi_decode_kernel.h`) as a lax.scan.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core import dispatch
from .core.tensor import Tensor
from .ops._helpers import as_tensor
from .io import Dataset


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """potentials [B, T, N], transition [N, N] (+2 rows/cols when
    include_bos_eos_tag, matching the reference layout where the last two
    tags are BOS/EOS). `lengths` [B] masks padded timesteps (required
    input in the reference; defaults to full length here).
    Returns (scores [B], paths [B, T])."""
    potentials = as_tensor(potentials)
    transition_params = as_tensor(transition_params)
    B, T, N = potentials.shape
    if lengths is None:
        lengths = np.full((B,), T, np.int32)
    lengths = as_tensor(lengths)

    def _fn(pot, trans, lens):
        if include_bos_eos_tag:
            start = trans[-2][:N]
            stop = trans[:N, -1]
            trans_core = trans[:N, :N]
        else:
            start = jnp.zeros((N,))
            stop = jnp.zeros((N,))
            trans_core = trans

        alpha0 = pot[:, 0] + start[None, :]
        ident = jnp.broadcast_to(jnp.arange(N)[None, :], (B, N))

        def step(alpha, xs):
            emit, t = xs
            valid = (t < lens)[:, None]            # [B,1]
            scores = alpha[:, :, None] + trans_core[None]
            best = jnp.max(scores, axis=1) + emit
            back = jnp.argmax(scores, axis=1)
            # frozen past each sequence's end: alpha carries, backpointer
            # is identity so backtracking repeats the final tag
            alpha_new = jnp.where(valid, best, alpha)
            back = jnp.where(valid, back, ident)
            return alpha_new, back

        ts = jnp.arange(1, T)
        alpha_f, backs = jax.lax.scan(
            step, alpha0, (jnp.swapaxes(pot[:, 1:], 0, 1), ts))
        alpha_f = alpha_f + stop[None, :]
        scores = jnp.max(alpha_f, axis=-1)
        last = jnp.argmax(alpha_f, axis=-1)

        def backtrack(carry, back):
            tag = carry
            prev = jnp.take_along_axis(back, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(backtrack, last, backs, reverse=True)
        paths = jnp.concatenate([jnp.swapaxes(path_rev, 0, 1),
                                 last[:, None]], axis=1)
        return scores, paths.astype(jnp.int32)
    return dispatch.apply("viterbi_decode", _fn,
                          (potentials, transition_params, lengths))


class _SyntheticTextDataset(Dataset):
    def __init__(self, size, seq_len, vocab, n_classes, seed):
        rng = np.random.RandomState(seed)
        self.x = rng.randint(1, vocab, (size, seq_len)).astype(np.int64)
        self.y = rng.randint(0, n_classes, (size,)).astype(np.int64)

    def __getitem__(self, idx):
        return self.x[idx], np.array([self.y[idx]], np.int64)

    def __len__(self):
        return len(self.x)


class Imdb(_SyntheticTextDataset):
    """API-shaped stand-in (zero-egress image)."""

    def __init__(self, mode="train", cutoff=150):
        super().__init__(2000 if mode == "train" else 400, 64, 5000, 2,
                         0 if mode == "train" else 1)


class UCIHousing(Dataset):
    def __init__(self, mode="train"):
        rng = np.random.RandomState(2 if mode == "train" else 3)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(np.float32)

    def __getitem__(self, idx):
        return self.x[idx], np.array([self.y[idx]], np.float32)

    def __len__(self):
        return len(self.x)


class Imikolov(Dataset):
    """PTB n-gram LM dataset stand-in (`text/datasets/imikolov.py`):
    samples are `N`-tuples of word ids — (n-1 context words, target) in
    'NGRAM' mode, (src seq, trg seq) pairs in 'SEQ' mode."""

    def __init__(self, mode="train", data_type="NGRAM", window_size=5,
                 min_word_freq=50):
        rng = np.random.RandomState(4 if mode == "train" else 5)
        n = 2000 if mode == "train" else 400
        vocab = 2074  # the real PTB cutoff-50 vocab size
        self.data_type = data_type.upper()
        if self.data_type == "NGRAM":
            self.data = [tuple(rng.randint(0, vocab, (window_size,))
                               .astype(np.int64))
                         for _ in range(n)]
        elif self.data_type == "SEQ":
            self.data = [(rng.randint(0, vocab, (window_size,))
                          .astype(np.int64),
                          rng.randint(0, vocab, (window_size,))
                          .astype(np.int64)) for _ in range(n)]
        else:
            raise ValueError("data_type must be NGRAM or SEQ")

    def __getitem__(self, idx):
        d = self.data[idx]
        return tuple(np.array(x) for x in d)

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """ml-1m stand-in (`text/datasets/movielens.py`): each sample is
    (user_id, gender, age, job, movie_id, category_ids, title_ids,
    rating) as int/float arrays — the reference's tuple-of-arrays
    contract."""

    def __init__(self, mode="train", test_ratio=0.1, rand_seed=0):
        rng = np.random.RandomState(rand_seed + (0 if mode == "train"
                                                 else 1))
        n = 2000 if mode == "train" else 200
        self.data = []
        for _ in range(n):
            self.data.append((
                np.array([rng.randint(1, 6041)], np.int64),   # user id
                np.array([rng.randint(0, 2)], np.int64),      # gender
                np.array([rng.randint(0, 7)], np.int64),      # age bucket
                np.array([rng.randint(0, 21)], np.int64),     # job
                np.array([rng.randint(1, 3953)], np.int64),   # movie id
                rng.randint(0, 18, (rng.randint(1, 4),))
                .astype(np.int64),                            # categories
                rng.randint(1, 5175, (rng.randint(1, 9),))
                .astype(np.int64),                            # title words
                np.array([float(rng.randint(1, 6))], np.float32)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL stand-in (`text/datasets/conll05.py`): sample =
    (pred_idx, mark, word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
    label) — the 9-field tuple the reference emits per instance."""

    WORD_DICT = 44068
    PRED_DICT = 3162
    LABEL_DICT = 59

    def __init__(self, mode="train"):
        rng = np.random.RandomState(6 if mode == "train" else 7)
        n = 1000 if mode == "train" else 200
        self.data = []
        for _ in range(n):
            T = rng.randint(5, 40)
            word = rng.randint(0, self.WORD_DICT, (T,)).astype(np.int64)
            ctxs = [np.roll(word, s) for s in (2, 1, 0, -1, -2)]
            self.data.append((
                np.array([rng.randint(0, self.PRED_DICT)], np.int64),
                (rng.rand(T) < 0.1).astype(np.int64),   # predicate mark
                word, *[c.astype(np.int64) for c in ctxs],
                rng.randint(0, self.LABEL_DICT, (T,)).astype(np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMT(Dataset):
    """Seq2seq (src_ids, trg_ids, trg_ids_next) triples with <s>/<e>
    framing — `text/datasets/wmt14.py` / `wmt16.py` contract."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, mode, dict_size, seed):
        rng = np.random.RandomState(seed)
        n = 1000 if mode == "train" else 200
        self.dict_size = dict_size
        self.data = []
        for _ in range(n):
            ls, lt = rng.randint(3, 30), rng.randint(3, 30)
            src = rng.randint(3, dict_size, (ls,)).astype(np.int64)
            trg = rng.randint(3, dict_size, (lt,)).astype(np.int64)
            trg_in = np.concatenate([[self.BOS], trg]).astype(np.int64)
            trg_next = np.concatenate([trg, [self.EOS]]).astype(np.int64)
            self.data.append((src, trg_in, trg_next))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_WMT):
    def __init__(self, mode="train", dict_size=30000):
        super().__init__(mode, dict_size, 8 if mode == "train" else 9)


class WMT16(_WMT):
    def __init__(self, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        super().__init__(mode, max(src_dict_size, trg_dict_size),
                         10 if mode == "train" else 11)


class ViterbiDecoder:
    """`paddle.text.ViterbiDecoder` layer: holds the transition matrix
    and decodes (potentials, lengths) -> (scores, paths)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = as_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
