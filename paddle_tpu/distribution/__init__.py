"""paddle_tpu.distribution — probability distributions.

Parity: `python/paddle/distribution/` (Distribution, Normal, Uniform,
Categorical, Bernoulli, Beta, Dirichlet, Exponential family bits,
kl_divergence) over jax.random + jax.scipy.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as rng
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor
from ..core import dispatch


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc, dtype="float32")
        self.scale = as_tensor(scale, dtype="float32")
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        key = rng.next_key()
        out_shape = shape + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        eps = jax.random.normal(key, out_shape)
        return Tensor(self.loc._data + eps * self.scale._data)

    rsample = sample

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return dispatch.apply("normal_log_prob", _fn,
                              (value, self.loc, self.scale))

    def entropy(self):
        def _fn(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return dispatch.apply("normal_entropy", _fn, (self.scale,))

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low, dtype="float32")
        self.high = as_tensor(high, dtype="float32")
        super().__init__(tuple(self.low.shape))

    def sample(self, shape=(), seed=0):
        key = rng.next_key()
        out_shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape))
        u = jax.random.uniform(key, out_shape)
        return Tensor(self.low._data + u * (self.high._data
                                            - self.low._data))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return dispatch.apply("uniform_log_prob", _fn,
                              (value, self.low, self.high))

    def entropy(self):
        def _fn(lo, hi):
            return jnp.log(hi - lo)
        return dispatch.apply("uniform_entropy", _fn,
                              (self.low, self.high))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits, dtype="float32")
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        key = rng.next_key()
        out = jax.random.categorical(
            key, self.logits._data, shape=tuple(shape)
            + tuple(self.logits.shape[:-1]))
        # reference returns int64; canonical int on TPU is int32
        return Tensor(out.astype(jnp.int32))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return dispatch.apply("categorical_log_prob", _fn,
                              (value, self.logits))

    def probs(self, value=None):
        from ..nn import functional as F
        p = F.softmax(self.logits)
        if value is None:
            return p
        from .. import ops
        return ops.take_along_axis(p, as_tensor(value).unsqueeze(-1),
                                   axis=-1)

    def entropy(self):
        def _fn(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return dispatch.apply("categorical_entropy", _fn, (self.logits,))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = as_tensor(probs, dtype="float32")
        super().__init__(tuple(self.probs_.shape))

    def sample(self, shape=()):
        key = rng.next_key()
        out_shape = tuple(shape) + tuple(self.probs_.shape)
        return Tensor(jax.random.bernoulli(
            key, self.probs_._data, out_shape).astype(jnp.float32))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return dispatch.apply("bernoulli_log_prob", _fn,
                              (value, self.probs_))

    def entropy(self):
        def _fn(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return dispatch.apply("bernoulli_entropy", _fn, (self.probs_,))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = as_tensor(alpha, dtype="float32")
        self.beta = as_tensor(beta, dtype="float32")
        super().__init__(tuple(self.alpha.shape))

    def sample(self, shape=()):
        key = rng.next_key()
        out_shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.alpha._data.shape, self.beta._data.shape))
        return Tensor(jax.random.beta(key, self.alpha._data,
                                      self.beta._data, out_shape))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.gammaln(a)
                       + jax.scipy.special.gammaln(b)
                       - jax.scipy.special.gammaln(a + b)))
        return dispatch.apply("beta_log_prob", _fn,
                              (value, self.alpha, self.beta))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = as_tensor(concentration, dtype="float32")
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    def sample(self, shape=()):
        key = rng.next_key()
        return Tensor(jax.random.dirichlet(
            key, self.concentration._data, tuple(shape)
            + tuple(self.concentration.shape[:-1])))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, c):
            return (jnp.sum((c - 1) * jnp.log(v), axis=-1)
                    + jax.scipy.special.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jax.scipy.special.gammaln(c), axis=-1))
        return dispatch.apply("dirichlet_log_prob", _fn,
                              (value, self.concentration))


def kl_divergence(p, q):
    """paddle.distribution.kl_divergence parity for the common pairs."""
    from .. import ops
    if isinstance(p, Normal) and isinstance(q, Normal):
        def _fn(l1, s1, l2, s2):
            var1, var2 = s1 * s1, s2 * s2
            return (jnp.log(s2 / s1) + (var1 + (l1 - l2) ** 2)
                    / (2 * var2) - 0.5)
        return dispatch.apply("kl_normal", _fn,
                              (p.loc, p.scale, q.loc, q.scale))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        def _fn(lg1, lg2):
            lp1 = jax.nn.log_softmax(lg1, -1)
            lp2 = jax.nn.log_softmax(lg2, -1)
            return jnp.sum(jnp.exp(lp1) * (lp1 - lp2), axis=-1)
        return dispatch.apply("kl_categorical", _fn,
                              (p.logits, q.logits))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        def _fn(lo1, hi1, lo2, hi2):
            return jnp.log((hi2 - lo2) / (hi1 - lo1))
        return dispatch.apply("kl_uniform", _fn,
                              (p.low, p.high, q.low, q.high))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        def _fn(p1, p2):
            p1 = jnp.clip(p1, 1e-7, 1 - 1e-7)
            p2 = jnp.clip(p2, 1e-7, 1 - 1e-7)
            return (p1 * (jnp.log(p1) - jnp.log(p2))
                    + (1 - p1) * (jnp.log1p(-p1) - jnp.log1p(-p2)))
        return dispatch.apply("kl_bernoulli", _fn, (p.probs_, q.probs_))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions
    (`python/paddle/distribution/exponential_family.py`): entropy via
    Bregman divergence of the log-normalizer is available when
    `_natural_parameters`/`_log_normalizer` are defined."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError


class Multinomial(Distribution):
    """`python/paddle/distribution/multinomial.py`: counts over k
    categories from `total_count` draws."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = as_tensor(probs, dtype="float32")
        super().__init__(tuple(self.probs.shape[:-1]),
                         (self.probs.shape[-1],))

    def sample(self, shape=()):
        k = self.probs.shape[-1]
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        key = rng.next_key()
        draws = jax.random.categorical(
            key, jnp.log(p), axis=-1,
            shape=tuple(shape) + (self.total_count,)
            + tuple(self.probs.shape[:-1]))
        onehot = jax.nn.one_hot(draws, k)
        # sum over the draw axis (first of the appended axes)
        counts = onehot.sum(axis=len(tuple(shape)))
        return Tensor(counts)

    def log_prob(self, value):
        v = as_tensor(value, dtype="float32")._data
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        logc = (jax.scipy.special.gammaln(self.total_count + 1.0)
                - jax.scipy.special.gammaln(v + 1.0).sum(-1))
        return Tensor(logc + (v * jnp.log(p)).sum(-1))

    @property
    def mean(self):
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        return Tensor(self.total_count * p)

    @property
    def variance(self):
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        return Tensor(self.total_count * p * (1 - p))


class Independent(Distribution):
    """Reinterprets `reinterpreted_batch_rank` trailing batch dims as
    event dims (`python/paddle/distribution/independent.py`)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        super().__init__(bshape[: len(bshape) - self.rank],
                         bshape[len(bshape) - self.rank:]
                         + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        arr = lp._data
        for _ in range(self.rank):
            arr = arr.sum(-1)
        return Tensor(arr)

    def entropy(self):
        e = self.base.entropy()
        arr = e._data
        for _ in range(self.rank):
            arr = arr.sum(-1)
        return Tensor(arr)


# ------------------------------------------------------------ transforms


class Transform:
    """`python/paddle/distribution/transform.py` base: forward/inverse +
    log|det J|."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self.forward_log_det_jacobian(
            self.inverse(y))._data)

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, dtype="float32")
        self.scale = as_tensor(scale, dtype="float32")

    def forward(self, x):
        return Tensor(self.loc._data
                      + self.scale._data * as_tensor(x)._data)

    def inverse(self, y):
        return Tensor((as_tensor(y)._data - self.loc._data)
                      / self.scale._data)

    def forward_log_det_jacobian(self, x):
        return Tensor(jnp.broadcast_to(
            jnp.log(jnp.abs(self.scale._data)),
            as_tensor(x)._data.shape))


class ExpTransform(Transform):
    def forward(self, x):
        return Tensor(jnp.exp(as_tensor(x)._data))

    def inverse(self, y):
        return Tensor(jnp.log(as_tensor(y)._data))

    def forward_log_det_jacobian(self, x):
        return Tensor(as_tensor(x)._data)


class SigmoidTransform(Transform):
    def forward(self, x):
        return Tensor(jax.nn.sigmoid(as_tensor(x)._data))

    def inverse(self, y):
        v = as_tensor(y)._data
        return Tensor(jnp.log(v) - jnp.log1p(-v))

    def forward_log_det_jacobian(self, x):
        v = as_tensor(x)._data
        return Tensor(-jax.nn.softplus(-v) - jax.nn.softplus(v))


class TanhTransform(Transform):
    def forward(self, x):
        return Tensor(jnp.tanh(as_tensor(x)._data))

    def inverse(self, y):
        return Tensor(jnp.arctanh(as_tensor(y)._data))

    def forward_log_det_jacobian(self, x):
        v = as_tensor(x)._data
        return Tensor(2.0 * (jnp.log(2.0) - v - jax.nn.softplus(-2 * v)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)._data
            total = j if total is None else total + j
            x = t.forward(x)
        return Tensor(total)


class TransformedDistribution(Distribution):
    """`python/paddle/distribution/transformed_distribution.py`: push a
    base distribution through a Transform; log_prob via change of
    variables."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(list(transforms))
        super().__init__(tuple(base.batch_shape),
                         tuple(base.event_shape))

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        base_lp = self.base.log_prob(x)._data
        ildj = self.transform.forward_log_det_jacobian(x)._data
        return Tensor(base_lp - ildj)
