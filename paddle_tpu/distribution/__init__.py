"""paddle_tpu.distribution — probability distributions.

Parity: `python/paddle/distribution/` (Distribution, Normal, Uniform,
Categorical, Bernoulli, Beta, Dirichlet, Exponential family bits,
kl_divergence) over jax.random + jax.scipy.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as rng
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor
from ..core import dispatch


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc, dtype="float32")
        self.scale = as_tensor(scale, dtype="float32")
        super().__init__(tuple(self.loc.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        key = rng.next_key()
        out_shape = shape + tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape))
        eps = jax.random.normal(key, out_shape)
        return Tensor(self.loc._data + eps * self.scale._data)

    rsample = sample

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, loc, scale):
            var = scale * scale
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)
        return dispatch.apply("normal_log_prob", _fn,
                              (value, self.loc, self.scale))

    def entropy(self):
        def _fn(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return dispatch.apply("normal_entropy", _fn, (self.scale,))

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low, dtype="float32")
        self.high = as_tensor(high, dtype="float32")
        super().__init__(tuple(self.low.shape))

    def sample(self, shape=(), seed=0):
        key = rng.next_key()
        out_shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape))
        u = jax.random.uniform(key, out_shape)
        return Tensor(self.low._data + u * (self.high._data
                                            - self.low._data))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return dispatch.apply("uniform_log_prob", _fn,
                              (value, self.low, self.high))

    def entropy(self):
        def _fn(lo, hi):
            return jnp.log(hi - lo)
        return dispatch.apply("uniform_entropy", _fn,
                              (self.low, self.high))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits, dtype="float32")
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        key = rng.next_key()
        out = jax.random.categorical(
            key, self.logits._data, shape=tuple(shape)
            + tuple(self.logits.shape[:-1]))
        # reference returns int64; canonical int on TPU is int32
        return Tensor(out.astype(jnp.int32))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return dispatch.apply("categorical_log_prob", _fn,
                              (value, self.logits))

    def probs(self, value=None):
        from ..nn import functional as F
        p = F.softmax(self.logits)
        if value is None:
            return p
        from .. import ops
        return ops.take_along_axis(p, as_tensor(value).unsqueeze(-1),
                                   axis=-1)

    def entropy(self):
        def _fn(lg):
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return dispatch.apply("categorical_entropy", _fn, (self.logits,))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = as_tensor(probs, dtype="float32")
        super().__init__(tuple(self.probs_.shape))

    def sample(self, shape=()):
        key = rng.next_key()
        out_shape = tuple(shape) + tuple(self.probs_.shape)
        return Tensor(jax.random.bernoulli(
            key, self.probs_._data, out_shape).astype(jnp.float32))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return dispatch.apply("bernoulli_log_prob", _fn,
                              (value, self.probs_))

    def entropy(self):
        def _fn(p):
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return dispatch.apply("bernoulli_entropy", _fn, (self.probs_,))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = as_tensor(alpha, dtype="float32")
        self.beta = as_tensor(beta, dtype="float32")
        super().__init__(tuple(self.alpha.shape))

    def sample(self, shape=()):
        key = rng.next_key()
        out_shape = tuple(shape) + tuple(jnp.broadcast_shapes(
            self.alpha._data.shape, self.beta._data.shape))
        return Tensor(jax.random.beta(key, self.alpha._data,
                                      self.beta._data, out_shape))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, a, b):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.gammaln(a)
                       + jax.scipy.special.gammaln(b)
                       - jax.scipy.special.gammaln(a + b)))
        return dispatch.apply("beta_log_prob", _fn,
                              (value, self.alpha, self.beta))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = as_tensor(concentration, dtype="float32")
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    def sample(self, shape=()):
        key = rng.next_key()
        return Tensor(jax.random.dirichlet(
            key, self.concentration._data, tuple(shape)
            + tuple(self.concentration.shape[:-1])))

    def log_prob(self, value):
        value = as_tensor(value)

        def _fn(v, c):
            return (jnp.sum((c - 1) * jnp.log(v), axis=-1)
                    + jax.scipy.special.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jax.scipy.special.gammaln(c), axis=-1))
        return dispatch.apply("dirichlet_log_prob", _fn,
                              (value, self.concentration))


def kl_divergence(p, q):
    """paddle.distribution.kl_divergence parity for the common pairs."""
    from .. import ops
    if isinstance(p, Normal) and isinstance(q, Normal):
        def _fn(l1, s1, l2, s2):
            var1, var2 = s1 * s1, s2 * s2
            return (jnp.log(s2 / s1) + (var1 + (l1 - l2) ** 2)
                    / (2 * var2) - 0.5)
        return dispatch.apply("kl_normal", _fn,
                              (p.loc, p.scale, q.loc, q.scale))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        def _fn(lg1, lg2):
            lp1 = jax.nn.log_softmax(lg1, -1)
            lp2 = jax.nn.log_softmax(lg2, -1)
            return jnp.sum(jnp.exp(lp1) * (lp1 - lp2), axis=-1)
        return dispatch.apply("kl_categorical", _fn,
                              (p.logits, q.logits))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        def _fn(lo1, hi1, lo2, hi2):
            return jnp.log((hi2 - lo2) / (hi1 - lo1))
        return dispatch.apply("kl_uniform", _fn,
                              (p.low, p.high, q.low, q.high))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        def _fn(p1, p2):
            p1 = jnp.clip(p1, 1e-7, 1 - 1e-7)
            p2 = jnp.clip(p2, 1e-7, 1 - 1e-7)
            return (p1 * (jnp.log(p1) - jnp.log(p2))
                    + (1 - p1) * (jnp.log1p(-p1) - jnp.log1p(-p2)))
        return dispatch.apply("kl_bernoulli", _fn, (p.probs_, q.probs_))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions
    (`python/paddle/distribution/exponential_family.py`): subclasses
    defining `_natural_parameters`/`_log_normalizer` get entropy() for
    free via the Bregman identity H = logZ - <eta, grad logZ> (+ mean
    carrier measure, assumed 0 as in the reference)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nat = [as_tensor(p, dtype="float32")._data
               for p in self._natural_parameters]
        # per-element grads via grad-of-sum; entropy stays batch-shaped
        # (reference reduces nothing beyond the elementwise eta*grad)
        grads = jax.grad(
            lambda *ns: jnp.sum(self._log_normalizer(*ns)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = -self._mean_carrier_measure + self._log_normalizer(*nat)
        for eta, g in zip(nat, grads):
            ent = ent - eta * g
        return Tensor(ent)


class Multinomial(Distribution):
    """`python/paddle/distribution/multinomial.py`: counts over k
    categories from `total_count` draws."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = as_tensor(probs, dtype="float32")
        super().__init__(tuple(self.probs.shape[:-1]),
                         (self.probs.shape[-1],))

    def sample(self, shape=()):
        k = self.probs.shape[-1]
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        key = rng.next_key()
        draws = jax.random.categorical(
            key, jnp.log(p), axis=-1,
            shape=tuple(shape) + (self.total_count,)
            + tuple(self.probs.shape[:-1]))
        onehot = jax.nn.one_hot(draws, k)
        # sum over the draw axis (first of the appended axes)
        counts = onehot.sum(axis=len(tuple(shape)))
        return Tensor(counts)

    def log_prob(self, value):
        v = as_tensor(value, dtype="float32")
        n = float(self.total_count)

        def f(val, pr):
            pn = pr / pr.sum(-1, keepdims=True)
            logc = (jax.scipy.special.gammaln(n + 1.0)
                    - jax.scipy.special.gammaln(val + 1.0).sum(-1))
            # xlogy: count 0 with prob 0 contributes 0, not 0 * -inf
            return logc + jax.scipy.special.xlogy(val, pn).sum(-1)

        return dispatch.apply("multinomial_log_prob", f, (v, self.probs))

    @property
    def mean(self):
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        return Tensor(self.total_count * p)

    @property
    def variance(self):
        p = self.probs._data / self.probs._data.sum(-1, keepdims=True)
        return Tensor(self.total_count * p * (1 - p))


class Independent(Distribution):
    """Reinterprets `reinterpreted_batch_rank` trailing batch dims as
    event dims (`python/paddle/distribution/independent.py`)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = tuple(base.batch_shape)
        if not 0 <= self.rank <= len(bshape):
            raise ValueError(
                f"reinterpreted_batch_rank {self.rank} out of range for "
                f"base batch_shape {bshape}")
        super().__init__(bshape[: len(bshape) - self.rank],
                         bshape[len(bshape) - self.rank:]
                         + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        from .. import ops
        lp = self.base.log_prob(value)
        for _ in range(self.rank):
            lp = ops.sum(lp, axis=-1)
        return lp

    def entropy(self):
        from .. import ops
        e = self.base.entropy()
        for _ in range(self.rank):
            e = ops.sum(e, axis=-1)
        return e


# ------------------------------------------------------------ transforms


class Transform:
    """`python/paddle/distribution/transform.py` base: forward/inverse +
    log|det J|."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    """All transform math routes through dispatched ops so gradients flow
    through the tape (MLE on transformed distributions needs d log_prob /
    d params)."""

    def __init__(self, loc, scale):
        self.loc = as_tensor(loc, dtype="float32")
        self.scale = as_tensor(scale, dtype="float32")

    def forward(self, x):
        return self.loc + self.scale * as_tensor(x)

    def inverse(self, y):
        return (as_tensor(y) - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        shp = tuple(as_tensor(x).shape)
        return dispatch.apply(
            "affine_ldj",
            lambda s: jnp.broadcast_to(jnp.log(jnp.abs(s)), shp),
            (self.scale,))


class ExpTransform(Transform):
    def forward(self, x):
        from .. import ops
        return ops.exp(as_tensor(x))

    def inverse(self, y):
        from .. import ops
        return ops.log(as_tensor(y))

    def forward_log_det_jacobian(self, x):
        return as_tensor(x)


class SigmoidTransform(Transform):
    def forward(self, x):
        x = as_tensor(x)
        return dispatch.apply("sigmoid_t", jax.nn.sigmoid, (x,))

    def inverse(self, y):
        y = as_tensor(y)
        return dispatch.apply(
            "logit_t", lambda v: jnp.log(v) - jnp.log1p(-v), (y,))

    def forward_log_det_jacobian(self, x):
        x = as_tensor(x)
        return dispatch.apply(
            "sigmoid_ldj",
            lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v), (x,))


class TanhTransform(Transform):
    def forward(self, x):
        x = as_tensor(x)
        return dispatch.apply("tanh_t", jnp.tanh, (x,))

    def inverse(self, y):
        y = as_tensor(y)
        return dispatch.apply("arctanh_t", jnp.arctanh, (y,))

    def forward_log_det_jacobian(self, x):
        x = as_tensor(x)
        return dispatch.apply(
            "tanh_ldj",
            lambda v: 2.0 * (jnp.log(2.0) - v - jax.nn.softplus(-2 * v)),
            (x,))


class StickBreakingTransform(Transform):
    """`distribution/transform.py StickBreakingTransform` parity:
    unconstrained R^K <-> the (K+1)-simplex via the stick-breaking
    construction (logit offsets against the remaining stick)."""

    def forward(self, x):
        x = as_tensor(x)

        def _fn(v):
            K = v.shape[-1]
            offset = jnp.log(K - jnp.arange(K, dtype=v.dtype))
            z = jax.nn.sigmoid(v - offset)
            zpad = jnp.concatenate(
                [z, jnp.ones(v.shape[:-1] + (1,), v.dtype)], axis=-1)
            one_minus = jnp.concatenate(
                [jnp.ones(v.shape[:-1] + (1,), v.dtype), 1 - z], axis=-1)
            return zpad * jnp.cumprod(one_minus, axis=-1)
        return dispatch.apply("stickbreaking_t", _fn, (x,))

    def inverse(self, y):
        y = as_tensor(y)

        def _fn(p):
            K = p.shape[-1] - 1
            offset = jnp.log(K - jnp.arange(K, dtype=p.dtype))
            cum = jnp.concatenate(
                [jnp.zeros(p.shape[:-1] + (1,), p.dtype),
                 jnp.cumsum(p[..., :-1], axis=-1)], axis=-1)[..., :K]
            rest = 1.0 - cum
            z = p[..., :K] / jnp.maximum(rest, 1e-30)
            return jnp.log(z) - jnp.log1p(-z) + offset
        return dispatch.apply("stickbreaking_inv", _fn, (y,))

    def forward_log_det_jacobian(self, x):
        x = as_tensor(x)

        def _fn(v):
            K = v.shape[-1]
            offset = jnp.log(K - jnp.arange(K, dtype=v.dtype))
            u = v - offset
            z = jax.nn.sigmoid(u)
            one_minus = jnp.concatenate(
                [jnp.ones(v.shape[:-1] + (1,), v.dtype), 1 - z], axis=-1)
            rest = jnp.cumprod(one_minus, axis=-1)[..., :K]
            return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rest),
                           axis=-1)
        return dispatch.apply("stickbreaking_ldj", _fn, (x,))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else total + j
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """`python/paddle/distribution/transformed_distribution.py`: push a
    base distribution through a Transform; log_prob via change of
    variables."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(list(transforms))
        super().__init__(tuple(base.batch_shape),
                         tuple(base.event_shape))

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        from .. import ops
        x = self.transform.inverse(value)
        base_lp = self.base.log_prob(x)
        ildj = self.transform.forward_log_det_jacobian(x)
        # elementwise transforms: reduce the per-element Jacobian over
        # the base's event dims so it matches base_lp's shape
        for _ in range(len(self.base.event_shape)):
            ildj = ops.sum(ildj, axis=-1)
        return base_lp - ildj
