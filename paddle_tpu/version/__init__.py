"""`paddle.version` parity (`python/paddle/version.py`, generated at
build time in the reference). TPU build: static metadata + the live jax
backend versions."""
full_version = "3.0.0-tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
commit = "tpu-native"
istaged = False

cuda_version = "False"      # reference prints 'False' on non-CUDA builds
cudnn_version = "False"
xpu_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}\nminor: {minor}\npatch: {patch}\nrc: {rc}")
    print(f"commit: {commit}")
    import jax
    print(f"jax: {jax.__version__}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
