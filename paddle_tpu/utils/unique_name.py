"""`paddle.utils.unique_name` parity
(`python/paddle/utils/unique_name.py` over fluid's UniqueNameGenerator):
process-wide name uniquifier with guard/switch scoping."""
from __future__ import annotations

import contextlib
import threading


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.prefix = prefix
        self.ids = {}
        self._lock = threading.Lock()

    def __call__(self, key):
        with self._lock:
            n = self.ids.get(key, 0)
            self.ids[key] = n + 1
        return "_".join([self.prefix + key, str(n)]) if self.prefix \
            else f"{key}_{n}"


_generator = UniqueNameGenerator()


def generate(key):
    return _generator(key)


def switch(new_generator=None):
    """Replace the global generator; returns the old one."""
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh generator (names restart inside the guard)."""
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
