from . import cpp_extension  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(module_name, err_msg=None):
    """`paddle.utils.try_import` parity."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"Failed to import {module_name}. "
                          f"Install it first.") from e
