"""`paddle.sysconfig` parity (`python/paddle/sysconfig.py`): include/lib
directories — here the package's C ABI headers live beside the native
PS engine sources."""
import os

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory containing the framework's C/C++ sources/headers
    (the native PS engine csrc)."""
    return os.path.join(_ROOT, "ps", "csrc")


def get_lib():
    """Directory containing the built native library (libps_core.so)."""
    return os.path.join(_ROOT, "ps")
