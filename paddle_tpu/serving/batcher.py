"""Token-budget batching + sampling heads for the serving engine.

This module owns the pieces `incubate/nn/generation.py` and the
continuous-batching engine share (generation.py imports them from here):

* `SamplingConfig` / `select_token` — the greedy/sampling head applied
  to one step's logits, device-side.
* `next_pow2` / `round_up` — the power-of-two shape discipline every
  compiled entry point uses so shapes come from a tiny closed set.
* `pack_step` — pack one engine iteration (decode tokens + prefill
  chunks) into the FIXED `[token_budget]` flat-token layout of the
  mixed step, so admission/eviction never changes a compiled shape.

The flat-token step protocol (the "Ragged Paged Attention" shape
discipline — one compiled program serves a churning request mix):

    token_ids    [T] int32  — decode tokens and prefill-chunk tokens,
                              concatenated; 0 past num_tokens
    slot_ids     [T] int32  — owning slot per token; -1 = padding
    positions    [T] int32  — position of the token in its sequence
    sample_index [S] int32  — per slot, the index in [0, T) of the
                              token whose hidden state samples that
                              slot's next token; -1 = no sample this
                              step (mid-prefill)

Every array has the same shape every step; `block_tables` (from the
paged KV cache) rides next to them.
"""
from __future__ import annotations

import collections
import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    strategy: str = "greedy"       # "greedy" | "sampling"
    temperature: float = 1.0
    top_k: int = 0                 # 0 = off
    top_p: float = 1.0             # 1.0 = off
    repetition_penalty: float = 1.0   # 1.0 = off (HF semantics)
    presence_penalty: float = 0.0     # 0.0 = off (additive, one-shot)
    frequency_penalty: float = 0.0    # 0.0 = off (count-scaled)
    penalty_window: int = 128      # tokens of context the penalties see


def needs_history(sc: SamplingConfig) -> bool:
    """True when `select_token` wants the per-slot token-history input
    (any logit processor active) — the engine then packs a fixed
    `[max_slots, penalty_window]` history tensor into the mixed step."""
    return (sc.repetition_penalty != 1.0 or sc.presence_penalty != 0.0
            or sc.frequency_penalty != 0.0)


def apply_count_penalties(logits, counts, sc: SamplingConfig):
    """Repetition / presence / frequency processors from a token-count
    histogram (ISSUE 19 device-resident form).

    logits [..., V]; counts [..., Vb] — per-context occurrence counts
    over `Vb` vocab bins (bin of token t is t % Vb; Vb == V is exact,
    smaller Vb trades penalty precision for state size —
    docs/SERVING.md). The count tensor is what the multi-tick engine
    keeps resident on device and updates per accepted token, so the
    processors advance inside the decode while_loop without a host
    history rebuild. Any leading batch shape works: the speculative
    verify path passes per-position [S, K, Vb] prior counts.

    * repetition (HF semantics): seen tokens' logits are divided by
      the penalty when positive, multiplied when negative.
    * presence: a flat subtraction per seen token (one-shot).
    * frequency: a COUNT-SCALED subtraction — each occurrence in the
      window adds another `frequency_penalty`, so chronic repeaters
      are pushed down harder than one-off mentions (the OpenAI-style
      companion of the one-shot presence penalty)."""
    import jax.numpy as jnp
    V = logits.shape[-1]
    Vb = counts.shape[-1]
    cnt = counts.astype(logits.dtype)
    if Vb != V:
        cnt = cnt[..., jnp.arange(V, dtype=jnp.int32) % Vb]
    seen = cnt > 0
    if sc.repetition_penalty != 1.0:
        rp = float(sc.repetition_penalty)
        logits = jnp.where(
            seen, jnp.where(logits > 0, logits / rp, logits * rp),
            logits)
    if sc.presence_penalty != 0.0:
        logits = logits - float(sc.presence_penalty) * seen.astype(
            logits.dtype)
    if sc.frequency_penalty != 0.0:
        logits = logits - float(sc.frequency_penalty) * cnt
    return logits


def history_to_counts(history, vocab_bins, dtype=None):
    """[B, W] -1-padded token history -> [B, vocab_bins] float counts:
    ONE scatter-add (duplicates coalesce; -1 padding scatters weight
    0). The bridge between the host-rebuilt history tensor and the
    count-histogram form `apply_count_penalties` consumes."""
    import jax.numpy as jnp
    dtype = dtype or jnp.float32
    valid = history >= 0
    idx = jnp.where(valid, history % int(vocab_bins), 0)
    return jnp.zeros((history.shape[0], int(vocab_bins)), dtype).at[
        jnp.arange(history.shape[0])[:, None], idx].add(
        valid.astype(dtype))


def apply_logit_penalties(logits, history, sc: SamplingConfig):
    """Repetition / presence / frequency processors from a [B, W]
    -1-padded token-history window (the host-rebuilt form
    `incubate/nn/generation.py` feeds). Exactly
    `apply_count_penalties` over the history's exact-vocab count
    histogram — one scatter, then the shared count math, so the two
    entry points can never disagree on penalty semantics."""
    return apply_count_penalties(
        logits, history_to_counts(history, logits.shape[-1],
                                  dtype=logits.dtype), sc)


def filter_logits(logits, sc: SamplingConfig):
    """The temperature / top-k / top-p logit transform of the sampling
    strategy, factored out so the speculative verify path can reuse
    it: the distribution non-speculative sampling draws from is
    EXACTLY `softmax(filter_logits(logits, sc))`, and the rejection
    rule must target that same distribution (serving/engine.py)."""
    import jax
    import jax.numpy as jnp
    if sc.temperature != 1.0:
        logits = logits / max(sc.temperature, 1e-6)
    if sc.top_k and sc.top_k > 0:
        kth = jax.lax.top_k(logits, sc.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e9, logits)
    if sc.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p; the
        # cutoff is the SMALLEST kept logit
        keep = cum - probs < sc.top_p
        kth = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                      keepdims=True)
        logits = jnp.where(logits < kth, -1e9, logits)
    return logits


def select_token(logits, key, sc: SamplingConfig, history=None,
                 counts=None):
    """logits [B, V] -> token [B] int32 (device-side sampling).

    `history` [B, W] int32 (-1 pad) or `counts` [B, Vb] (the
    device-resident histogram form, ISSUE 19) feeds the repetition/
    presence/frequency logit processors; they compose with greedy AND
    the top-k/top-p/temperature path (penalties first, then the
    strategy)."""
    import jax
    import jax.numpy as jnp
    logits = logits.astype(jnp.float32)
    if counts is not None and needs_history(sc):
        logits = apply_count_penalties(logits, counts, sc)
    elif history is not None and needs_history(sc):
        logits = apply_logit_penalties(logits, history, sc)
    if sc.strategy == "greedy":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits, sc)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def next_pow2(n, lo=16):
    p = lo
    while p < n:
        p *= 2
    return p


def round_up(n, m):
    return ((n + m - 1) // m) * m


def choose_token_budget(max_slots, block_size, requested=None,
                        verify_width=1, role="mixed",
                        reserve_region=False):
    """Per-step token budget: a power of two >= max(max_slots,
    2*block_size) so a full decode round always fits and prefill chunks
    cover at least two KV blocks per step (generation.py's bucket
    discipline applied to the step axis). An explicit `requested`
    budget is rounded up to a power of two and floored at `max_slots`
    (a budget below the slot count would stall resident requests
    forever while they hold KV blocks).

    With speculation on (`verify_width` = draft_k + 1 > 1) the first
    `max_slots * verify_width` flat tokens are the RESERVED verify
    region (see `pack_step`), so the floor rises to that region plus
    prefill room — a budget that left prefill zero tokens would starve
    admission forever.

    `role="decode"` (disaggregated serving, docs/SERVING.md) shrinks
    the DEFAULT: a decode-role replica admits migrated requests whose
    KV arrives by block transport, so its steps are decode-dominated
    and the budget only needs the decode/verify tokens plus a little
    prefill headroom (preempted migrants re-prefill locally; +1 keeps
    at least one prefill token even with every slot decoding). Every
    step pays the full fixed `[T]` compute whether or not prefill rides
    along — the small budget is where disaggregation's inter-token
    latency win comes from. Explicit `requested` always wins.

    `reserve_region=True` reserves the per-slot decode region even at
    `verify_width == 1` (block-sparse decode, ISSUE 15: the sparse
    engine routes the region through shortened block tables, so its
    tokens must sit at fixed per-slot indices) — the floors follow the
    speculative treatment."""
    vw = int(verify_width)
    region = max_slots * vw
    region_on = vw > 1 or reserve_region
    if requested is not None:
        floor = max_slots if not region_on else region + 1
        return next_pow2(max(int(requested), floor), lo=1)
    if role == "decode":
        return next_pow2(region + 1, lo=1)
    if not region_on:
        return next_pow2(max(max_slots, 2 * block_size))
    return next_pow2(region + 2 * block_size)


def prefill_chunk(remaining, budget_left):
    """Chunk size for one prefill slice under the remaining budget:
    the whole remainder when it fits, else the largest power of two
    <= budget_left (keeps chunk boundaries bucket-aligned so a long
    prompt is consumed in a handful of predictable slices)."""
    remaining = int(remaining)
    budget_left = int(budget_left)
    if budget_left <= 0 or remaining <= 0:
        return 0
    if remaining <= budget_left:
        return remaining
    p = 1
    while p * 2 <= budget_left:
        p *= 2
    return p


class FairQueue:
    """Bounded round-robin admission queue across tenants.

    The frontend's backpressure + fairness primitive: each tenant gets
    its own FIFO lane, `pop()` serves lanes round-robin so one chatty
    tenant cannot starve the others, and the TOTAL size is bounded —
    `push` refuses above `max_pending` and the async frontend turns
    that refusal into awaiting-for-space. Pure host-side and
    synchronous; all coordination lives in the frontend's event loop.
    """

    def __init__(self, max_pending=256):
        self.max_pending = int(max_pending)
        self._lanes = collections.OrderedDict()   # tenant -> deque
        self._size = 0

    def __len__(self):
        return self._size

    @property
    def full(self):
        return self._size >= self.max_pending

    def push(self, tenant, item):
        """False (item NOT queued) when the queue is at capacity."""
        if self.full:
            return False
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = self._lanes[tenant] = collections.deque()
        lane.append(item)
        self._size += 1
        return True

    def pop(self):
        """Next item, rotating across tenants; None when empty. A
        tenant whose lane still has items goes to the BACK of the
        rotation after serving one, so K tenants each get ~1/K of
        admissions regardless of lane depth."""
        while self._lanes:
            tenant, lane = next(iter(self._lanes.items()))
            self._lanes.move_to_end(tenant)
            if not lane:
                del self._lanes[tenant]
                continue
            item = lane.popleft()
            self._size -= 1
            if not lane:
                del self._lanes[tenant]
            return item
        return None

    def items(self):
        """Iterate queued items across all lanes (inspection only)."""
        for lane in self._lanes.values():
            yield from lane

    def remove(self, item):
        """Drop a queued item (cancellation before admission)."""
        for tenant, lane in list(self._lanes.items()):
            try:
                lane.remove(item)
            except ValueError:
                continue
            self._size -= 1
            if not lane:
                del self._lanes[tenant]
            return True
        return False


@dataclasses.dataclass
class StepPlan:
    """Host-side plan for one mixed step (fixed-shape numpy arrays)."""
    token_ids: np.ndarray       # [T] int32
    slot_ids: np.ndarray        # [T] int32, -1 pad
    positions: np.ndarray       # [T] int32
    sample_index: np.ndarray    # [max_slots] int32, -1 = no sample
    num_tokens: int             # real tokens this step
    decode_slots: list          # slots that fed decode/verify tokens
    prefill_done: list          # slots whose prompt completed this step
    prefill_tokens: int
    decode_tokens: int
    verify_width: int = 1       # 1 + draft_k (1 = no speculation)
    decode_entries: list = dataclasses.field(default_factory=list)
    #                         [(slot, [tokens], position)] as planned —
    #                         the engine replays these against the
    #                         verify logits to compute accept lengths


class PlanBuffers:
    """Reusable numpy backing for `pack_step`'s fixed-shape tensors.

    The multi-tick engine (docs/SERVING.md, "Device-resident decode")
    keeps TWO of these and ping-pongs between dispatches: dispatch k's
    arrays may still be feeding an async host→device transfer while
    the host packs dispatch k+1 into the other buffer, so packing
    never scribbles over an in-flight plan (the PR 6 double-buffer
    prefetch discipline applied to the engine's plan tensors)."""

    def __init__(self, token_budget, max_slots):
        self.token_ids = np.zeros(token_budget, np.int32)
        self.slot_ids = np.full(token_budget, -1, np.int32)
        self.positions = np.zeros(token_budget, np.int32)
        self.sample_index = np.full(max_slots, -1, np.int32)

    def reset(self):
        self.token_ids[:] = 0
        self.slot_ids[:] = -1
        self.positions[:] = 0
        self.sample_index[:] = -1


def pack_step(token_budget, max_slots, decode, prefills,
              verify_width=1, reserve_region=False,
              buffers: PlanBuffers = None) -> StepPlan:
    """Pack decode entries + prefill chunks into the flat-token layout.

    decode: [(slot, token_or_tokens, position)] — one entry per running
        decode. A scalar token is the plain one-token decode; a list
        [last, d_1..d_k] is a speculative verify group (k <= draft_k
        proposed tokens after the last accepted one).
    prefills: [(slot, chunk_tokens: ndarray, start_pos, completes)] —
        `completes` marks the chunk that reaches the end of the prompt
        (its last token's hidden state samples the slot's first output).

    Layout: with `verify_width == 1` decode tokens pack densely from
    index 0 and prefill chunks follow (the PR 2 layout, unchanged).
    With speculation (`verify_width` = draft_k + 1 > 1) the first
    `max_slots * verify_width` flat tokens are a FIXED verify region —
    slot s owns indices [s*vw, (s+1)*vw) — so the compiled step can
    reshape it to `[max_slots, vw]` and run the verify-shaped paged
    attention + per-position logits without any gather indices that
    change shape as the decode mix churns; prefill packs after the
    region. `reserve_region=True` applies the same fixed per-slot
    layout at `verify_width == 1` (block-sparse decode, ISSUE 15:
    decode token of slot s sits at flat index s, and its hidden state
    still samples through `sample_index` like the dense layout).

    `buffers` (a `PlanBuffers`) reuses preallocated arrays instead of
    allocating fresh ones — same layout, same contents."""
    vw = int(verify_width)
    region_on = vw > 1 or reserve_region
    region = max_slots * vw if region_on else 0
    if buffers is not None:
        buffers.reset()
        token_ids = buffers.token_ids
        slot_ids = buffers.slot_ids
        positions = buffers.positions
        sample_index = buffers.sample_index
    else:
        token_ids = np.zeros(token_budget, np.int32)
        slot_ids = np.full(token_budget, -1, np.int32)
        positions = np.zeros(token_budget, np.int32)
        sample_index = np.full(max_slots, -1, np.int32)
    i = 0
    decode_slots = []
    decode_entries = []
    n_decode = 0
    for slot, tok, pos in decode:
        toks = [int(tok)] if np.isscalar(tok) or getattr(
            tok, "ndim", None) == 0 else [int(t) for t in tok]
        if len(toks) > max(vw, 1):
            raise ValueError(
                f"decode group of {len(toks)} tokens exceeds the "
                f"verify width {max(vw, 1)}")
        base = slot * vw if region_on else i
        token_ids[base:base + len(toks)] = toks
        slot_ids[base:base + len(toks)] = slot
        positions[base:base + len(toks)] = np.arange(
            pos, pos + len(toks), dtype=np.int32)
        if vw == 1:
            sample_index[slot] = base
            if not region_on:
                i += 1
        decode_slots.append(slot)
        decode_entries.append((slot, toks, int(pos)))
        n_decode += len(toks)
    if region_on:
        i = region
    n = n_decode + sum(len(c[1]) for c in prefills) \
        + (region - n_decode if region_on else 0)
    if n > token_budget:
        raise ValueError(f"plan of {n} tokens exceeds token budget "
                         f"{token_budget}")
    prefill_done = []
    n_prefill = 0
    for slot, chunk, start, completes in prefills:
        m = len(chunk)
        token_ids[i:i + m] = chunk
        slot_ids[i:i + m] = slot
        positions[i:i + m] = np.arange(start, start + m, dtype=np.int32)
        if completes:
            sample_index[slot] = i + m - 1
            prefill_done.append(slot)
        i += m
        n_prefill += m
    return StepPlan(token_ids=token_ids, slot_ids=slot_ids,
                    positions=positions, sample_index=sample_index,
                    num_tokens=i, decode_slots=decode_slots,
                    prefill_done=prefill_done,
                    prefill_tokens=n_prefill,
                    decode_tokens=n_decode, verify_width=vw,
                    decode_entries=decode_entries)
