"""Declarative SLO plane over the request-trace event stream (ISSUE 16).

`SLOMonitor` subscribes to `serving.tracing.TRACER` as an observer:
every first token feeds a per-tenant TTFT sample, every decode/verify
emit an inter-token-gap sample, every terminal outcome a deadline
verdict. Objectives are declared per tenant (`SLOConfig`) and
evaluated over **sliding-window quantile estimators** — a bounded
(ts, value) reservoir pruned to `window_s`, so a burst two windows ago
cannot mask a breach now. `evaluate()` publishes the per-tenant
gauges (`paddle_tpu_serving_slo_*`), computes the burn rate
(measured / target) per objective, and fires edge-triggered breach
callbacks on ok → burning transitions — the exact feed ROADMAP item
3's SLO-driven autoscaler consumes.

Everything is host-side and pull-based: observing a sample is an
O(1) deque append under no lock (observers run on the recording
thread), quantiles are computed only inside `evaluate()`.
"""
from __future__ import annotations

import collections
import dataclasses
import math
import time

from ..profiler import metrics as _pmetrics
from . import metrics as _smetrics
from . import tracing as _tracing

__all__ = ["SlidingWindowQuantile", "SLOConfig", "SLOMonitor",
           "DEFAULT_OBJECTIVES"]

#: objective name -> default target. ttft_p95 / inter_token_p99 are
#: seconds; deadline_miss_rate is a windowed fraction of terminal
#: requests that expired or finished past their deadline.
DEFAULT_OBJECTIVES = {
    "ttft_p95": 0.5,
    "inter_token_p99": 0.25,
    "deadline_miss_rate": 0.05,
}

#: objective -> the per-tenant gauge its measured value lands on
_OBJECTIVE_GAUGES = {
    "ttft_p95": "SERVING_SLO_TTFT_P95",
    "inter_token_p99": "SERVING_SLO_INTER_TOKEN_P99",
    "deadline_miss_rate": "SERVING_SLO_DEADLINE_MISS_RATIO",
}


class SlidingWindowQuantile:
    """Time-windowed reservoir: (ts, value) pairs pruned to the last
    `window_s` seconds, hard-capped at `max_samples` (oldest dropped
    first, counted). Quantiles are linear-interpolated over the sorted
    window — numpy.percentile semantics, so tests can cross-check."""

    def __init__(self, window_s=60.0, max_samples=2048):
        self.window_s = float(window_s)
        self.max_samples = max(1, int(max_samples))
        self._samples = collections.deque()
        self.dropped = 0
        self.total = 0

    def observe(self, value, ts):
        self.total += 1
        if len(self._samples) >= self.max_samples:
            self._samples.popleft()
            self.dropped += 1
        self._samples.append((ts, float(value)))

    def _prune(self, now):
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def count(self, now):
        self._prune(now)
        return len(self._samples)

    def quantile(self, q, now):
        """q in [0, 1]; None when the window is empty."""
        self._prune(now)
        if not self._samples:
            return None
        vals = sorted(v for _, v in self._samples)
        pos = q * (len(vals) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac


@dataclasses.dataclass
class SLOConfig:
    """Declarative objectives: `default` applies to every tenant,
    `tenants[name]` overrides per objective. `burn_threshold` is the
    burn rate (measured / target) above which an objective counts as
    breached — 1.0 means the target itself is the alert line."""

    default: dict = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_OBJECTIVES))
    tenants: dict = dataclasses.field(default_factory=dict)
    window_s: float = 60.0
    max_samples: int = 2048
    burn_threshold: float = 1.0

    def targets_for(self, tenant):
        targets = dict(self.default)
        targets.update(self.tenants.get(tenant, {}))
        return targets

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        unknown = set(d) - {"default", "tenants", "window_s",
                            "max_samples", "burn_threshold"}
        if unknown:
            raise ValueError(f"unknown SLOConfig keys: {sorted(unknown)}")
        return cls(**d)


class SLOMonitor:
    """Tracer observer + evaluator. `attach()` enables tracing (the SLO
    plane rides the trace event stream — there is no second feed) and
    subscribes; `evaluate()` turns the windows into a report, the
    registry gauges, and edge-triggered `on_breach` callbacks."""

    def __init__(self, config=None, clock=time.monotonic):
        self.config = config if config is not None else SLOConfig()
        if isinstance(self.config, dict):
            self.config = SLOConfig.from_dict(self.config)
        self.clock = clock
        self._ttft = {}        # tenant -> SlidingWindowQuantile
        self._inter = {}
        self._outcomes = {}    # tenant -> deque[(ts, missed)]
        self._burning = {}     # (tenant, objective) -> bool
        self._callbacks = []
        self.breaches = 0

    # ------------------------------------------------------ lifecycle
    def attach(self):
        _tracing.enable()
        _tracing.TRACER.add_observer(self)
        return self

    def detach(self):
        _tracing.TRACER.remove_observer(self)
        return self

    def __enter__(self):
        return self.attach()

    def __exit__(self, *a):
        self.detach()

    def on_breach(self, cb):
        """cb(tenant, objective, burn_rate, measured, target) — fired
        once per ok -> burning transition (edge-triggered; recovery
        re-arms it)."""
        self._callbacks.append(cb)
        return cb

    # ----------------------------------------- tracer observer feed
    def _window(self, table, tenant):
        w = table.get(tenant)
        if w is None:
            w = table[tenant] = SlidingWindowQuantile(
                self.config.window_s, self.config.max_samples)
        return w

    def on_ttft(self, tenant, value, ts):
        self._window(self._ttft, tenant).observe(value, ts)

    def on_inter_token(self, tenant, value, ts):
        self._window(self._inter, tenant).observe(value, ts)

    def on_outcome(self, tenant, outcome, deadline_missed, ts):
        dq = self._outcomes.get(tenant)
        if dq is None:
            dq = self._outcomes[tenant] = collections.deque(
                maxlen=self.config.max_samples)
        dq.append((ts, bool(deadline_missed)))

    # ------------------------------------------------------ evaluate
    def _miss_rate(self, tenant, now):
        dq = self._outcomes.get(tenant)
        if not dq:
            return None, 0
        cutoff = now - self.config.window_s
        while dq and dq[0][0] < cutoff:
            dq.popleft()
        if not dq:
            return None, 0
        misses = sum(1 for _, m in dq if m)
        return misses / len(dq), len(dq)

    def _measure(self, tenant, objective, now):
        if objective == "ttft_p95":
            w = self._ttft.get(tenant)
            return ((w.quantile(0.95, now), w.count(now))
                    if w else (None, 0))
        if objective == "inter_token_p99":
            w = self._inter.get(tenant)
            return ((w.quantile(0.99, now), w.count(now))
                    if w else (None, 0))
        if objective == "deadline_miss_rate":
            return self._miss_rate(tenant, now)
        raise ValueError(f"unknown SLO objective: {objective!r}")

    def evaluate(self, now=None):
        """-> {tenant: {objective: {value, target, burn_rate, ok,
        samples}}} over tenants with either declared overrides or
        observed traffic. Objectives with an empty window are omitted
        (no data is not a breach)."""
        if now is None:
            now = self.clock()
        tenants = (set(self.config.tenants) | set(self._ttft)
                   | set(self._inter) | set(self._outcomes))
        report = {}
        for tenant in sorted(tenants):
            entry = {}
            for objective, target in sorted(
                    self.config.targets_for(tenant).items()):
                value, n = self._measure(tenant, objective, now)
                if value is None:
                    continue
                burn = (value / target) if target > 0 else math.inf
                ok = burn <= self.config.burn_threshold
                entry[objective] = {"value": value, "target": target,
                                    "burn_rate": burn, "ok": ok,
                                    "samples": n}
                if _pmetrics._enabled:
                    getattr(_smetrics, _OBJECTIVE_GAUGES[objective]) \
                        .labels(tenant).set(value)
                    _smetrics.SERVING_SLO_BURN_RATE.labels(
                        tenant, objective).set(
                        burn if math.isfinite(burn) else -1.0)
                key = (tenant, objective)
                if not ok and not self._burning.get(key, False):
                    self.breaches += 1
                    if _pmetrics._enabled:
                        _smetrics.SERVING_SLO_BREACHES.labels(
                            tenant, objective).inc()
                    for cb in list(self._callbacks):
                        try:
                            cb(tenant, objective, burn, value, target)
                        except Exception:
                            pass
                self._burning[key] = not ok
            if entry:
                report[tenant] = entry
        return report
