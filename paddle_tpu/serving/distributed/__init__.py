"""paddle_tpu.serving.distributed — multi-chip / multi-replica serving.

Two layers over the single-process serving stack (docs/SERVING.md,
"Distributed serving"):

* `tp_engine.TPServingEngine` — the ONE compiled mixed step and the
  paged KV block pools sharded over a 1-D `("mp",)` tensor-parallel
  mesh (or a 2-D `("ep", "mp")` mesh for MoE stacks:
  `expert_parallel=` shards the experts, TP x EP compose —
  docs/MOE.md): heads partitioned, block tables replicated,
  token-identical to the TP=1/EP=1 engine and still exactly one
  compile per engine.
* `router.ReplicaRouter` — asyncio ingress over N `ServingFrontend`
  replicas with prefix-affinity dispatch (a router-side shadow radix
  index estimates each replica's cached prefixes), queue-depth load
  balancing, health probes (`health.ReplicaHealth`) and lossless
  failover: a dead replica's in-flight requests re-submit elsewhere
  (prompts are re-prefillable; greedy outputs are identical).
* `transport.KVTransport` — block-granular KV movement for the
  DISAGGREGATED fleet (docs/SERVING.md "Disaggregated serving"):
  prefill-role replicas stream paged KV blocks (with their int8 scale
  rows) to decode-role replicas and hand live requests off at the
  first token; loaded decode replicas shed requests the same way.
  `ReplicaRouter(roles=..., migration=...)` orchestrates both.
"""
from .health import ReplicaHealth  # noqa: F401
from .router import (NoReplicaAvailable, ReplicaRouter,  # noqa: F401
                     ShadowRadixIndex)
from .tp_engine import TPServingEngine  # noqa: F401
from .transport import (BlockChunk, InProcessTransport,  # noqa: F401
                        KVTransport, MigrationTicket)

__all__ = ["TPServingEngine", "ReplicaRouter", "ReplicaHealth",
           "ShadowRadixIndex", "NoReplicaAvailable", "KVTransport",
           "InProcessTransport", "MigrationTicket", "BlockChunk"]
