"""Multi-replica router with prefix-affinity dispatch.

`ReplicaRouter` fronts N `ServingFrontend` replicas (each one engine —
plain or tensor-parallel) with the same asyncio `submit()`/`stream()`
surface a single frontend exposes, adding the scale-out policies:

* **Prefix-affinity dispatch.** Each replica's radix prefix cache only
  pays off when same-prefix requests LAND on it, so the router keeps a
  `ShadowRadixIndex` — a block-aligned token trie per replica,
  recording the prompts (and chat-turn outputs) it has dispatched
  there. A new request is routed to the replica whose shadow tree
  holds its longest cached-prefix estimate (>= one full KV block),
  ties and misses falling back to least-loaded. The shadow tree is an
  ESTIMATE — the replica may have evicted the blocks — but a stale hit
  only costs a normal prefill, never correctness.
* **Queue-depth load balancing.** Load per replica = frontend
  admission queue + engine FIFO + resident slots + router dispatches
  not yet admitted; exported per replica as
  `paddle_tpu_serving_router_replica_queue_depth`.
* **Health + lossless failover.** `ReplicaHealth` probes each
  frontend's step-loop task; dispatch skips dead replicas, and an
  in-flight stream races its token queue against the replica's down
  event. On a replica death the request re-submits elsewhere and the
  router suppresses the tokens the caller already received — prompts
  are re-prefillable, so nothing is lost; with greedy sampling the
  re-generated tokens are identical (sampled requests may diverge
  after a failover, same as any re-submission).

Everything is in-process asyncio (the CPU test harness runs 2+
replicas in one process); the replica boundary is the
`ServingFrontend` API, so a multi-host transport can slot in behind
the same router later.
"""
from __future__ import annotations

import asyncio
import heapq
import itertools

from ...profiler import metrics as _pmetrics
from .. import metrics as smetrics
from .. import tracing as _tracing
from ..frontend import (DeadlineExceeded, FrontendClosed,
                        RequestCancelled, RequestMigrated)
from .health import ReplicaHealth


class NoReplicaAvailable(Exception):
    """Every replica is down (or none was configured)."""


class _ReplicaDied(Exception):
    """Internal: the dispatch replica died mid-stream (down event)."""


#: exceptions that MAY mean "the REPLICA failed", not "the REQUEST
#: failed": a stopped/crashed frontend (FrontendClosed) or an
#: engine/step-loop error (RuntimeError — e.g. a crashed mixed step;
#: the step loop fails every handle of that replica with it). The
#: router confirms with a health probe before failing over: a live
#: replica can raise RuntimeError for ONE request (the engine-stall
#: path fails the affected handles and keeps serving), and treating
#: that as replica death would let a single oversized request mark
#: every healthy replica down in turn.
_FAILOVER_ERRORS = (FrontendClosed, RuntimeError, _ReplicaDied)


class _ShadowNode:
    __slots__ = ("children", "stamp", "parent", "key")

    def __init__(self, stamp=0, parent=None, key=None):
        self.children = {}          # block token tuple -> _ShadowNode
        self.stamp = stamp
        self.parent = parent        # None once evicted (and for roots)
        self.key = key              # this node's chunk in parent.children


class ShadowRadixIndex:
    """Router-side estimate of each replica's radix prefix cache.

    One trie per replica over BLOCK-ALIGNED token chunks (the same
    granularity `serving.prefix_cache` caches at — partial tail blocks
    are never cached, so they never count toward affinity either).
    Bounded: beyond `capacity_blocks` nodes per replica, the
    oldest-stamped leaves are evicted — mirroring, approximately, the
    LRU the real cache applies under pool pressure."""

    def __init__(self, block_size, capacity_blocks=4096):
        self.bs = int(block_size)
        self.cap = int(capacity_blocks)
        self._roots = {}                   # replica -> _ShadowNode
        self._counts = {}                  # replica -> node count
        self._heaps = {}                   # replica -> [(stamp, seq, node)]
        self._tick = itertools.count(1)
        self._seq = itertools.count()      # heap tie-breaker

    def _chunks(self, tokens):
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + self.bs])
                for i in range(0, len(toks) - self.bs + 1, self.bs)]

    def match(self, replica, tokens):
        """Longest cached-prefix estimate, in TOKENS (block multiple)."""
        node = self._roots.get(replica)
        if node is None:
            return 0
        stamp = next(self._tick)
        n = 0
        for chunk in self._chunks(tokens):
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            nxt.stamp = stamp             # touch: affinity reads keep
            n += self.bs                  # hot paths resident
            node = nxt
        if n and not node.children:
            # the touched tail is a leaf: record the fresh stamp in the
            # eviction heap so the touch actually protects it
            self._push(replica, node)
        return n

    def insert(self, replica, tokens):
        root = self._roots.get(replica)
        if root is None:
            root = self._roots[replica] = _ShadowNode()
            self._counts[replica] = 0
            self._heaps[replica] = []
        stamp = next(self._tick)
        node = root
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                child = node.children[chunk] = _ShadowNode(
                    stamp, node, chunk)
                self._counts[replica] += 1
            child.stamp = stamp
            node = child
        if node is not root and not node.children:
            self._push(replica, node)
        self._evict(replica)

    def remove(self, replica, tokens):
        """Forget `tokens`' path on `replica`: the deepest matched
        nodes are deleted bottom-up while they are CHILDLESS, so a
        prefix other inserted prompts still extend survives — only the
        suffix unique to this token sequence goes. This is the
        migration update (`on_migrate`): a request's chat-turn KV left
        the replica, so its unique tail must stop attracting affinity
        there, while the shared family head (still in the replica's
        real prefix cache) keeps steering. Returns nodes removed."""
        root = self._roots.get(replica)
        if root is None:
            return 0
        node, path = root, []
        for chunk in self._chunks(tokens):
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            path.append(nxt)
            node = nxt
        removed = 0
        keep = root
        for node in reversed(path):
            parent = node.parent
            if (node.children or parent is None
                    or parent.children.get(node.key) is not node):
                keep = node
                break
            del parent.children[node.key]
            node.parent = None
            self._counts[replica] -= 1
            removed += 1
        else:
            keep = root
        if keep is not root and not keep.children:
            # the surviving tail node just became a leaf: give the
            # eviction heap an entry at its current stamp
            self._push(replica, keep)
        return removed

    def on_migrate(self, src, dst, tokens):
        """A live request (prompt + generated output = `tokens`) moved
        from `src` to `dst`, blocks and all: move its affinity with it
        so later same-head requests steer at the KV's NEW home instead
        of the stale copy (the dispatch-time-only learning bug this
        method closes — docs/SERVING.md, "Disaggregated serving")."""
        self.remove(src, tokens)
        self.insert(dst, tokens)

    def drop(self, replica):
        """Forget a replica's whole tree (it died; its cache is gone)."""
        self._roots.pop(replica, None)
        self._counts.pop(replica, None)
        self._heaps.pop(replica, None)

    def size(self, replica):
        return self._counts.get(replica, 0)

    def _push(self, replica, node):
        heapq.heappush(self._heaps[replica],
                       (node.stamp, next(self._seq), node))

    def _evict(self, replica):
        # lazy-deletion min-heap over leaf stamps: every live leaf's
        # LATEST stamp has an entry (pushed on creation and on every
        # touch), so popping until a valid one is amortized O(log n)
        # per eviction — this runs on the per-request dispatch path,
        # where the old full-trie rescan per evicted leaf was O(cap)
        heap = self._heaps.get(replica)
        root = self._roots.get(replica)
        while self._counts.get(replica, 0) > self.cap and heap:
            stamp, _, node = heapq.heappop(heap)
            parent = node.parent
            if (node.stamp != stamp or node.children or parent is None
                    or parent.children.get(node.key) is not node):
                continue                  # stale entry: touched,
            del parent.children[node.key]  # re-parented or already gone
            node.parent = None
            self._counts[replica] -= 1
            if parent is not root and not parent.children:
                # the parent just became an evictable leaf
                self._push(replica, parent)


class ReplicaRouter:
    """Prefix-affinity dispatch over N serving frontends.

    Usage::

        router = ReplicaRouter([fe0, fe1])
        async with router:
            toks = await router.submit(prompt, max_new_tokens=32)
            async for tok in router.stream(prompt2, tenant="b"):
                ...

    `policy` is "affinity" (shadow-radix longest-prefix, falling back
    to least-loaded) or "round_robin" (the baseline the affinity
    contract in tools/router_smoke.py is measured against).
    """

    #: auto-shed policy defaults (`migration=True`): a decode replica
    #: sheds one live request per tick while its load exceeds the
    #: lightest decode replica's by >= `imbalance`
    MIGRATION_DEFAULTS = {"imbalance": 4, "interval": 0.05,
                          "max_per_tick": 1}

    def __init__(self, frontends, *, policy="affinity",
                 shadow_capacity=4096, probe_interval=0.05,
                 roles=None, transport=None, migration=None):
        if not frontends:
            raise ValueError("ReplicaRouter needs at least one replica")
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown policy {policy!r}")
        self.frontends = list(frontends)
        self.policy = policy
        self.health = ReplicaHealth(self.frontends)
        bs = {fe.engine.block_size for fe in self.frontends}
        if len(bs) != 1:
            raise ValueError(
                f"replicas disagree on block_size: {sorted(bs)}")
        # ---- disaggregated roles (docs/SERVING.md) ------------------
        n = len(self.frontends)
        if roles is None:
            roles = ["mixed"] * n
        roles = [str(r) for r in roles]
        if len(roles) != n or any(
                r not in ("mixed", "prefill", "decode") for r in roles):
            raise ValueError(f"roles must be one of mixed/prefill/"
                             f"decode per replica, got {roles}")
        for i, r in enumerate(roles):
            er = getattr(self.frontends[i].engine, "role", "mixed")
            if (r == "prefill") != (er == "prefill"):
                raise ValueError(
                    f"replica {i}: router role {r!r} but engine role "
                    f"{er!r} — a prefill replica needs an engine built "
                    "with role='prefill' (and only those hand off)")
        self.roles = roles
        self.disagg = any(r != "mixed" for r in roles)
        self._dispatch_targets = [i for i, r in enumerate(roles)
                                  if r in ("prefill", "mixed")]
        self._decode_targets = [i for i, r in enumerate(roles)
                                if r in ("decode", "mixed")]
        if self.disagg and (not self._dispatch_targets
                            or not self._decode_targets):
            raise ValueError(
                "a disaggregated fleet needs at least one prefill-"
                f"capable AND one decode-capable replica, got {roles}")
        if self.disagg or migration:
            metas = {tuple(sorted(fe.engine.kv.kv_meta().items()))
                     for fe in self.frontends}
            if len(metas) != 1:
                raise ValueError(
                    "migration needs identical KV geometry on every "
                    f"replica, got {sorted(metas)}")
            from .transport import InProcessTransport
            self.transport = (transport if transport is not None
                              else InProcessTransport())
        else:
            self.transport = transport
        self.migration = None
        if migration:
            if not self.disagg:
                # the monolithic stream path has no RequestMigrated
                # handler — auto-shedding there would end healthy
                # streams with an unhandled migration ticket
                raise ValueError(
                    "migration= needs a disaggregated fleet (roles "
                    "with decode replicas); a monolithic fleet "
                    "rebalances by dispatch, not by moving live KV")
            self.migration = dict(self.MIGRATION_DEFAULTS)
            if isinstance(migration, dict):
                self.migration.update(migration)
        self.shadow = ShadowRadixIndex(bs.pop(),
                                       capacity_blocks=shadow_capacity)
        self.clock = self.frontends[0].engine.clock
        self.probe_interval = float(probe_interval)
        self._inflight = [0] * len(self.frontends)
        # quiesced replicas (fleet drain, ISSUE 17): excluded from NEW
        # dispatch/placement decisions but NOT marked down — in-flight
        # requests keep streaming to completion on their old replica
        # (mark_down would fire the down event and force a failover,
        # which is exactly what a graceful drain must not do)
        self._quiesced = set()
        self._rr = itertools.count()
        self._rr_decode = itertools.count()
        self._mseq = itertools.count()
        self._prober = None
        self._balancer = None
        # raw counters (always on; mirrored into the metrics registry
        # only when observability is enabled)
        self.dispatches = 0
        self.affinity_hits = 0
        self.adapter_affinity_hits = 0
        self.failovers = 0
        self.migrations = {"handoff": 0, "shed": 0}
        self.role_dispatches = {"mixed": 0, "prefill": 0, "decode": 0}

    # ---------------------------------------------------------- lifecycle
    async def start(self):
        for fe in self.frontends:
            await fe.start()
        loop = asyncio.get_running_loop()
        if self._prober is None:
            self._prober = loop.create_task(
                self.health.run(self.probe_interval))
        if self.migration and self._balancer is None:
            self._balancer = loop.create_task(self._balance_loop())
        return self

    async def stop(self):
        for task in (self._prober, self._balancer):
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        self._prober = self._balancer = None
        for i, fe in enumerate(self.frontends):
            if self.health.probe(i):
                await fe.stop()

    async def __aenter__(self):
        return await self.start()

    async def __aexit__(self, *exc):
        await self.stop()

    # ----------------------------------------------------------- dispatch
    def queue_depth(self, i):
        """The load the balancer compares: everything queued or
        resident on replica `i`, plus router dispatches in flight that
        its frontend may not have admitted yet."""
        fe = self.frontends[i]
        sch = fe.engine.scheduler
        return (len(fe._fair) + len(sch.queue) + sch.num_active
                + self._inflight[i])

    def _export_depths(self):
        if _pmetrics._enabled:
            for i in range(len(self.frontends)):
                smetrics.ROUTER_REPLICA_QUEUE_DEPTH.labels(str(i)).set(
                    self.queue_depth(i))

    def _shadow_note(self, idx, tokens, adapter_id):
        """Record `tokens` on replica `idx`'s shadow tree UNLESS the
        request runs under an adapter: adapter K/V never enters the
        replica's real prefix cache, so the shadow must not learn it
        either (a poisoned shadow would affinity-steer base prompts
        at KV that was never cached). The ONE place this rule lives —
        every dispatch path calls through here."""
        if adapter_id is None:
            self.shadow.insert(idx, tokens)

    def _shadow_migrate(self, src, dst, tokens, adapter_id):
        """`_shadow_note`'s companion for live migrations (same
        adapter-bypass rule)."""
        if adapter_id is None:
            self.shadow.on_migrate(src, dst, tokens)

    def _adapter_holders(self, live, adapter_id):
        """Replicas in `live` whose AdapterCache holds `adapter_id`
        resident right now (the adapter-affinity signal — like the
        shadow radix, a best-effort estimate: a stale pick only costs
        one slot-write load, never correctness)."""
        out = []
        for i in live:
            cache = getattr(self.frontends[i].engine, "adapters", None)
            if cache is not None and cache.resident(adapter_id):
                out.append(i)
        return out

    def _pick(self, prompt, adapter_id=None):
        """(replica index, affinity_hit) for one PROMPT dispatch —
        restricted to prefill-capable replicas in a disaggregated
        fleet. Raises NoReplicaAvailable when every candidate is down.

        Adapter affinity (ISSUE 14) filters FIRST: replicas whose
        AdapterCache already holds the request's adapter keep their
        warm slot (and skip a load), and the existing shadow-radix /
        least-loaded ladder breaks ties among them. No holder -> the
        full ladder decides and the landing replica loads the adapter
        cold at admission."""
        live = [i for i in self._dispatch_targets
                if i not in self._quiesced and self.health.alive(i)]
        if not live:
            raise NoReplicaAvailable(
                f"all {len(self._dispatch_targets)} prompt-dispatch "
                "replicas are down or quiesced")
        self.dispatches += 1
        if self.policy == "round_robin":
            idx = live[next(self._rr) % len(live)]
            self._shadow_note(idx, prompt, adapter_id)
            self._export_depths()
            return idx, False
        if adapter_id is not None:
            # adapter requests bypass the replica-side prefix cache
            # (their K/V is adapter-specific), so the shadow radix
            # neither matches nor learns them — residency + load
            # decide instead
            holders = self._adapter_holders(live, adapter_id)
            if holders:
                live = holders
                self.adapter_affinity_hits += 1
                if _pmetrics._enabled:
                    smetrics.ROUTER_ADAPTER_AFFINITY_HITS.inc()
            idx = min(live, key=lambda i: (self.queue_depth(i), i))
            self._export_depths()
            return idx, bool(holders)
        hits = {i: self.shadow.match(i, prompt) for i in live}
        best = max(hits.values())
        affinity = best >= self.shadow.bs        # >= one full KV block
        cands = [i for i in live if hits[i] == best] if affinity \
            else live
        idx = min(cands, key=lambda i: (self.queue_depth(i), i))
        if affinity:
            self.affinity_hits += 1
            if _pmetrics._enabled:
                smetrics.ROUTER_AFFINITY_HITS.inc()
        # record at DISPATCH time (not completion): concurrent requests
        # with the same head must converge on the same replica even
        # before the first one finishes prefill
        self.shadow.insert(idx, prompt)
        self._export_depths()
        return idx, affinity

    def _pick_decode(self, tokens, exclude=()):
        """Destination decode replica for a handoff or a shed
        migration: router-directed PLACEMENT — the shadow index knows
        where every prefix (and migrated chat turn) lives, so the
        request lands where its KV history already is when possible,
        least-loaded otherwise. Raises NoReplicaAvailable when no
        decode-capable replica (outside `exclude`) is up."""
        live = [i for i in self._decode_targets
                if i not in exclude and i not in self._quiesced
                and self.health.alive(i)]
        if not live:
            raise NoReplicaAvailable(
                "no decode-capable replica available "
                f"(roles={self.roles}, excluded={sorted(exclude)})")
        if self.policy == "round_robin":
            return live[next(self._rr_decode) % len(live)]
        hits = {i: self.shadow.match(i, tokens) for i in live}
        best = max(hits.values())
        cands = ([i for i in live if hits[i] == best]
                 if best >= self.shadow.bs else live)
        return min(cands, key=lambda i: (self.queue_depth(i), i))

    # ---------------------------------------------------- load shedding
    def shed(self, idx, n=1):
        """Manually ask replica `idx` to shed up to `n` live decodes;
        their streams re-place transparently via `RequestMigrated`.
        Returns how many were flagged."""
        return self.frontends[idx].shed(n)

    def rebalance(self):
        """One auto-shed decision (the `migration=` policy, also run
        periodically by the balance loop): when the most-loaded decode
        replica exceeds the least-loaded by >= `imbalance`, it sheds
        `max_per_tick` requests — the in-flight streams carry the KV
        to the lighter replica and the caller never notices. Returns
        requests flagged."""
        if not self.migration:
            return 0
        live = [i for i in self._decode_targets
                if i not in self._quiesced and self.health.alive(i)]
        if len(live) < 2:
            return 0
        depths = {i: self.queue_depth(i) for i in live}
        hi = max(live, key=lambda i: (depths[i], -i))
        lo = min(live, key=lambda i: (depths[i], i))
        if depths[hi] - depths[lo] < self.migration["imbalance"]:
            return 0
        return self.frontends[hi].shed(self.migration["max_per_tick"])

    # --------------------------------------- fleet lifecycle (ISSUE 17)
    def quiesce(self, idx):
        """Exclude replica `idx` from NEW dispatch/placement decisions
        while its in-flight requests stream to completion — the
        graceful half of a drain (health stays up; `mark_down` would
        failover the very requests a drain promises to finish)."""
        self._quiesced.add(idx)

    def unquiesce(self, idx):
        """Return a quiesced replica to rotation (upgrade flip done)."""
        self._quiesced.discard(idx)

    def is_drained(self, idx):
        """True when a quiesced replica holds NO work anywhere on its
        path: no router dispatches in flight, nothing in its
        frontend's fair queue or live set, and an idle engine."""
        fe = self.frontends[idx]
        sch = fe.engine.scheduler
        return (self._inflight[idx] == 0 and len(fe._fair) == 0
                and not fe._live and not sch.has_work)

    async def add_replica(self, frontend, role="mixed"):
        """Append one replica to the running fleet (fleet scale-up /
        rolling replacement). Indices are append-only — retirement
        quiesces + stops a replica but never reindexes, so in-flight
        streams and metric labels stay coherent. Validates the same
        invariants as construction (block size, role pairing, KV
        geometry for migrating fleets); starts the frontend when the
        router is already running. Returns the new index."""
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        if frontend.engine.block_size != \
                self.frontends[0].engine.block_size:
            raise ValueError(
                f"replica block_size {frontend.engine.block_size} != "
                f"fleet's {self.frontends[0].engine.block_size}")
        er = getattr(frontend.engine, "role", "mixed")
        if (role == "prefill") != (er == "prefill"):
            raise ValueError(
                f"router role {role!r} but engine role {er!r}")
        if self.disagg or self.migration:
            meta = tuple(sorted(frontend.engine.kv.kv_meta().items()))
            have = tuple(sorted(
                self.frontends[0].engine.kv.kv_meta().items()))
            if meta != have:
                raise ValueError(
                    "migration needs identical KV geometry on every "
                    "replica — new replica's kv_meta differs")
        idx = len(self.frontends)
        self.frontends.append(frontend)
        self.roles.append(str(role))
        self._inflight.append(0)
        if role in ("prefill", "mixed"):
            self._dispatch_targets.append(idx)
        if role in ("decode", "mixed"):
            self._decode_targets.append(idx)
        self.health.add(frontend)
        if self._prober is not None:
            await frontend.start()
        return idx

    async def _balance_loop(self):
        while True:
            await asyncio.sleep(self.migration["interval"])
            self.rebalance()

    # ------------------------------------------------- metric helpers
    def _count_role(self, role):
        self.role_dispatches[role] = self.role_dispatches.get(role, 0) + 1
        if _pmetrics._enabled:
            smetrics.ROUTER_DISPATCH_ROLE.labels(role).inc()

    def _note_migration(self, reason):
        self.migrations[reason] = self.migrations.get(reason, 0) + 1
        if _pmetrics._enabled:
            smetrics.ROUTER_MIGRATIONS.labels(reason).inc()

    def _fail_over(self, idx):
        """Common replica-death bookkeeping on a failover path."""
        self.health.mark_down(idx)
        self.shadow.drop(idx)
        self.failovers += 1
        self._count(idx, "failover")
        if _pmetrics._enabled:
            smetrics.ROUTER_FAILOVERS.inc()

    def _is_replica_death(self, idx, e):
        """Classify a _FAILOVER_ERRORS exception: True = replica `idx`
        is actually gone (fail over elsewhere); False = the replica is
        still serving and this was a per-REQUEST failure (e.g. the
        engine-stall RuntimeError for a working set its pool can't
        hold) — surface it, since re-submitting the same request to
        identical replicas would just stall them one by one. ONE
        definition for every dispatch path, so the probe-before-
        failover subtlety can't drift between them."""
        return isinstance(e, _ReplicaDied) or not self.health.probe(idx)

    # ------------------------------------------------------------ serving
    def register_adapter(self, adapter_id, weights):
        """Register a LoRA adapter on EVERY replica (migrating fleets
        need the registration wherever a request can land — failover
        re-prefills under the same adapter, and disagg tickets
        re-acquire a slot pin at the destination)."""
        for fe in self.frontends:
            fe.engine.register_adapter(adapter_id, weights)
        return adapter_id

    async def submit(self, prompt, max_new_tokens=32, *,
                     tenant="default", timeout=None, adapter_id=None):
        """Run one request to completion (with transparent failover);
        returns its generated token ids."""
        out = []
        async for tok in self.stream(prompt, max_new_tokens,
                                     tenant=tenant, timeout=timeout,
                                     adapter_id=adapter_id):
            out.append(tok)
        return out

    def _hold(self, idx):
        """Count a dispatch in replica `idx`'s load estimate only until
        its frontend admits it into the fair queue — from then on
        queue_depth sees it there (then in the engine FIFO / resident
        slots), and keeping it held for the whole request would
        double-count every admitted request. Returns (on_admitted
        callback, release-for-finally callback)."""
        self._inflight[idx] += 1
        pending = [True]

        def _admitted():
            if pending[0]:
                pending[0] = False
                self._inflight[idx] -= 1
                self._export_depths()

        def _release():
            if pending[0]:
                pending[0] = False
                self._inflight[idx] -= 1
            self._export_depths()

        return _admitted, _release

    def _remaining(self, idx, deadline):
        """Seconds left before `deadline` (None = no deadline); counts
        and raises when already past."""
        if deadline is None:
            return None
        remaining = deadline - self.clock()
        if remaining <= 0:
            self._count(idx, "expired")
            raise DeadlineExceeded()
        return remaining

    def _rname(self, idx):
        """Replica name for trace events — the engine's name when it
        has one (ISSUE 16 gives every engine one), else the index."""
        return getattr(self.frontends[idx].engine, "name",
                       f"replica{idx}")

    def _tclose(self, trace_id, outcome):
        """Close a trace from the router's side of the stream (caller
        abandoned the generator, deadline, error). Idempotent with the
        engine-side terminal hook — the first writer wins, so a normal
        finish/cancel recorded by the scheduler is never overwritten."""
        if trace_id is not None and _tracing._enabled:
            _tracing.TRACER.finish(trace_id, outcome, replica="router")

    async def stream(self, prompt, max_new_tokens=32, *,
                     tenant="default", timeout=None, adapter_id=None):
        """Async generator of generated tokens. On a replica death the
        request transparently re-submits to a live replica; tokens the
        caller already received are suppressed from the re-run. In a
        disaggregated fleet the stream spans the prefill replica, the
        block handoff and the decode replica (plus any shed hops) —
        see `_stream_disagg`."""
        if self.disagg:
            async for tok in self._stream_disagg(
                    prompt, max_new_tokens, tenant, timeout,
                    adapter_id=adapter_id):
                yield tok
            return
        deadline = (self.clock() + float(timeout)
                    if timeout is not None else None)
        delivered = 0
        # the trace id is minted ONCE per request, before the dispatch
        # loop: failover re-dispatches record onto the SAME trace (the
        # "dispatched" event reopens a trace the dying replica's cancel
        # path closed), so one stitched timeline survives the restart
        trace_id = (_tracing.TRACER.mint(tenant=str(tenant))
                    if _tracing._enabled else None)
        try:
            while True:
                idx, _ = self._pick(prompt, adapter_id=adapter_id)
                self._count_role("mixed")
                if _tracing._enabled:
                    _tracing.TRACER.event(trace_id, "dispatched",
                                          replica=self._rname(idx),
                                          role="mixed", tenant=tenant,
                                          version=self._version(idx))
                remaining = self._remaining(idx, deadline)
                on_admitted, release = self._hold(idx)
                attempt_out = []
                try:
                    agen = self.frontends[idx].stream(
                        prompt, max_new_tokens, tenant=tenant,
                        timeout=remaining, on_admitted=on_admitted,
                        adapter_id=adapter_id, trace_id=trace_id)
                    async for tok in self._attempt(idx, agen,
                                                   attempt_out):
                        if len(attempt_out) > delivered:
                            delivered += 1
                            yield tok
                    # replica finished the request: publish the chat
                    # turn to its shadow tree (the engine's
                    # finish-insert did the same with the real blocks;
                    # adapter requests never entered the real cache, so
                    # their shadow stays out too)
                    self._shadow_note(idx, list(prompt) + attempt_out,
                                      adapter_id)
                    self._count(idx, "finished")
                    return
                except _FAILOVER_ERRORS as e:
                    if not self._is_replica_death(idx, e):
                        self._count(idx, "error")
                        raise
                    self._fail_over(idx)
                    if _tracing._enabled:
                        _tracing.TRACER.event(
                            trace_id, "failover",
                            replica=self._rname(idx),
                            delivered=delivered)
                    continue                  # re-dispatch elsewhere
                except DeadlineExceeded:
                    self._count(idx, "expired")
                    raise
                except RequestCancelled:
                    self._count(idx, "cancelled")
                    raise
                except Exception:
                    self._count(idx, "error")
                    raise
                finally:
                    release()
        except DeadlineExceeded:
            self._tclose(trace_id, "expired")
            raise
        except (RequestCancelled, GeneratorExit,
                asyncio.CancelledError):
            self._tclose(trace_id, "cancelled")
            raise
        except BaseException:
            self._tclose(trace_id, "error")
            raise

    async def _stream_disagg(self, prompt, max_new_tokens, tenant,
                             timeout, adapter_id=None):
        """The disaggregated request pipeline, one async token stream:

        1. **Prefill dispatch** — affinity-steered over prefill-capable
           replicas; the handoff DESTINATION is chosen up front (shadow
           placement over decode replicas) so completed KV blocks
           stream ahead over the transport while prefill still runs.
        2. **Handoff** — the prefill frontend ends the attempt with
           `RequestMigrated(ticket)` after the first sampled token; the
           ticket (host state + tail blocks) ships to the destination,
           which imports the blocks and continues the stream
           mid-request, token-identically under greedy decoding.
        3. **Shed hops** — a loaded decode replica may end the attempt
           with another `RequestMigrated`; the request re-places onto a
           lighter decode replica (shadow entries move with it) and the
           stream continues seamlessly.
        4. **Failover** — a replica death restarts the whole pipeline
           (the KV payload died with the replica; prompts are
           re-prefillable) with already-delivered tokens suppressed.
        """
        deadline = (self.clock() + float(timeout)
                    if timeout is not None else None)
        prompt = list(prompt)
        delivered = 0
        transport = self.transport
        inbox = [None, None]                # (dst, key) awaiting collect
        # one trace id for the WHOLE pipeline: prefill dispatch,
        # stream-ahead, ticket transport, decode admission, shed hops
        # and failover restarts all stitch onto it (the ticket carries
        # it across the replica boundary)
        trace_id = (_tracing.TRACER.mint(tenant=str(tenant))
                    if _tracing._enabled else None)

        def _drop_inbox():
            if inbox[0] is not None:
                transport.drop(inbox[0], inbox[1])
                inbox[0] = inbox[1] = None

        try:
            while True:                     # failover restart loop
                pidx, _ = self._pick(prompt, adapter_id=adapter_id)
                self._count_role("prefill")
                if _tracing._enabled:
                    _tracing.TRACER.event(trace_id, "dispatched",
                                          replica=self._rname(pidx),
                                          role=self.roles[pidx],
                                          tenant=tenant,
                                          version=self._version(pidx))
                on_blocks = None
                didx = key = None
                if self.roles[pidx] == "prefill":
                    # handoff is certain: choose the destination now so
                    # completed blocks stream ahead of the ticket. A
                    # MIXED dispatch replica decodes locally instead —
                    # streaming its prompt KV ahead would pay a full
                    # export + codec round-trip dropped unconsumed for
                    # every request that never sheds.
                    didx = self._pick_decode(prompt)
                    key = f"req{next(self._mseq)}"
                    inbox[0], inbox[1] = didx, key
                    meta = self.frontends[pidx].engine.kv.kv_meta()

                    def _ship(chunk, p=pidx, d=didx, k=key, m=meta):
                        transport.send_chunk(p, d, k, m, chunk)

                    on_blocks = _ship
                remaining = self._remaining(pidx, deadline)
                on_admitted, release = self._hold(pidx)
                attempt_out = []
                ticket = None
                try:
                    agen = self.frontends[pidx].stream(
                        prompt, max_new_tokens, tenant=tenant,
                        timeout=remaining, on_admitted=on_admitted,
                        on_blocks=on_blocks, adapter_id=adapter_id,
                        trace_id=trace_id)
                    async for tok in self._attempt(pidx, agen,
                                                   attempt_out):
                        if len(attempt_out) > delivered:
                            delivered += 1
                            yield tok
                    # finished on the dispatch replica (a mixed replica
                    # serving end-to-end, or EOS/horizon at the prefill
                    # replica's first token): no migration happened
                    _drop_inbox()
                    self._shadow_note(pidx, prompt + attempt_out,
                                      adapter_id)
                    self._count(pidx, "finished")
                    return
                except RequestMigrated as e:
                    ticket = e.ticket
                except _FAILOVER_ERRORS as e:
                    _drop_inbox()
                    if not self._is_replica_death(pidx, e):
                        self._count(pidx, "error")
                        raise
                    self._fail_over(pidx)
                    if _tracing._enabled:
                        _tracing.TRACER.event(
                            trace_id, "failover",
                            replica=self._rname(pidx),
                            delivered=delivered)
                    continue
                except DeadlineExceeded:
                    self._count(pidx, "expired")
                    raise
                except RequestCancelled:
                    self._count(pidx, "cancelled")
                    raise
                except Exception:
                    self._count(pidx, "error")
                    raise
                finally:
                    release()

                # ---- migration out of the dispatch replica: a prefill
                # handoff (destination already receiving the stream-
                # ahead), or a mixed replica SHEDDING its live decode
                # (destination chosen now; every block rides the ticket)
                if didx is None:
                    path = list(ticket.prompt) + list(ticket.output)
                    didx = self._pick_decode(path, exclude=(pidx,))
                    key = f"req{next(self._mseq)}"
                    inbox[0], inbox[1] = didx, key
                    self._shadow_migrate(pidx, didx, path,
                                         adapter_id)
                    self._note_migration("shed")
                else:
                    self._note_migration("handoff")
                self._count(pidx, "migrated")
                hand_t0 = self.clock()
                transport.send_ticket(pidx, didx, key, ticket)
                restart = False
                while True:                 # decode phase + shed hops
                    assembled = transport.collect(didx, key)
                    inbox[0] = inbox[1] = None
                    self._count_role("decode")
                    if _tracing._enabled:
                        _tracing.TRACER.event(trace_id, "dispatched",
                                              replica=self._rname(didx),
                                              role="decode",
                                              tenant=tenant,
                                              version=self._version(didx))
                    # placement bookkeeping: the KV now lives on didx
                    history = (list(assembled.prompt)
                               + list(assembled.output))
                    self._shadow_note(didx, history, adapter_id)
                    remaining = self._remaining(didx, deadline)
                    on_admitted, release = self._hold(didx)
                    attempt_out = []
                    base = len(assembled.output)
                    gap_open = True
                    try:
                        agen = self.frontends[didx].stream_ticket(
                            assembled, on_admitted=on_admitted)
                        async for tok in self._attempt(didx, agen,
                                                       attempt_out):
                            if gap_open:
                                gap_open = False
                                if _pmetrics._enabled:
                                    smetrics.SERVING_HANDOFF_LATENCY \
                                        .observe(self.clock() - hand_t0)
                            if base + len(attempt_out) > delivered:
                                delivered += 1
                                yield tok
                        self._shadow_note(didx,
                                          history + attempt_out,
                                          adapter_id)
                        self._count(didx, "finished")
                        return
                    except RequestMigrated as e:
                        # shed: re-place on a lighter decode replica;
                        # the shadow entries move with the KV
                        t2 = e.ticket
                        old = didx
                        path = list(t2.prompt) + list(t2.output)
                        didx = self._pick_decode(path, exclude=(old,))
                        self._shadow_migrate(old, didx, path,
                                             adapter_id)
                        self._note_migration("shed")
                        self._count(old, "migrated")
                        key = f"req{next(self._mseq)}"
                        inbox[0], inbox[1] = didx, key
                        hand_t0 = self.clock()
                        transport.send_ticket(old, didx, key, t2)
                        continue
                    except _FAILOVER_ERRORS as e:
                        if not self._is_replica_death(didx, e):
                            self._count(didx, "error")
                            raise
                        # the KV payload died with the replica: restart
                        # from prefill, suppressing delivered tokens
                        self._fail_over(didx)
                        if _tracing._enabled:
                            _tracing.TRACER.event(
                                trace_id, "failover",
                                replica=self._rname(didx),
                                delivered=delivered)
                        restart = True
                        break
                    except DeadlineExceeded:
                        self._count(didx, "expired")
                        raise
                    except RequestCancelled:
                        self._count(didx, "cancelled")
                        raise
                    except Exception:
                        self._count(didx, "error")
                        raise
                    finally:
                        release()
                if not restart:
                    return
        except DeadlineExceeded:
            self._tclose(trace_id, "expired")
            raise
        except (RequestCancelled, GeneratorExit,
                asyncio.CancelledError):
            # caller abandoned the stream (or cancelled it) — possibly
            # mid-handoff, with the ticket still in the inbox; the
            # finally drops the inbox, this closes the trace
            self._tclose(trace_id, "cancelled")
            raise
        except BaseException:
            self._tclose(trace_id, "error")
            raise
        finally:
            _drop_inbox()

    async def _attempt(self, idx, agen, attempt_out):
        """One dispatch attempt against replica `idx`: forward the
        given frontend stream, racing the replica's down event
        (rescues requests stranded on a step-loop that died without
        failing its handles)."""
        q = asyncio.Queue()

        async def pump():
            try:
                async for tok in agen:
                    q.put_nowait(("tok", tok))
                q.put_nowait(("done", None))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                q.put_nowait(("err", e))

        async def watch():
            await self.health.down_event(idx).wait()
            q.put_nowait(("down", None))

        loop = asyncio.get_running_loop()
        tasks = (loop.create_task(pump()), loop.create_task(watch()))
        try:
            while True:
                kind, val = await q.get()
                if kind == "tok":
                    attempt_out.append(val)
                    yield val
                elif kind == "done":
                    return
                elif kind == "err":
                    raise val
                else:                          # down event fired
                    raise _ReplicaDied(f"replica {idx} died mid-stream")
        finally:
            # no awaits here: this finally also runs under GeneratorExit
            # when the caller abandons the stream. Cancelling the pump
            # closes fe.stream, whose own finally cancels the engine
            # request.
            for t in tasks:
                t.cancel()

    # ------------------------------------------------------------ helpers
    def _version(self, idx):
        """Replica `idx`'s checkpoint version label (ISSUE 17: rides
        router_requests_total and the dispatch trace spans, so a
        rolling upgrade is observable as the label migrating)."""
        return getattr(self.frontends[idx].engine, "weights_version",
                       "v0")

    def _count(self, idx, outcome):
        if _pmetrics._enabled:
            smetrics.ROUTER_REQUESTS.labels(
                str(idx), outcome, self._version(idx)).inc()

    def stats(self):
        """Router-side counters (always on, registry-independent)."""
        out = {"dispatches": self.dispatches,
               "affinity_hits": self.affinity_hits,
               "adapter_affinity_hits": self.adapter_affinity_hits,
               "failovers": self.failovers,
               "roles": list(self.roles),
               "quiesced": sorted(self._quiesced),
               "versions": [self._version(i)
                            for i in range(len(self.frontends))],
               "migrations": dict(self.migrations),
               "role_dispatches": dict(self.role_dispatches),
               "health": self.health.snapshot(),
               "queue_depths": [self.queue_depth(i) for i in
                                range(len(self.frontends))]}
        if self.transport is not None:
            out["transport"] = {
                "bytes_sent": self.transport.bytes_sent,
                "bytes_received": self.transport.bytes_received,
                "blocks_sent": self.transport.blocks_sent,
                "tickets_sent": self.transport.tickets_sent}
        return out
