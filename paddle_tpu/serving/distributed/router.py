"""Multi-replica router with prefix-affinity dispatch.

`ReplicaRouter` fronts N `ServingFrontend` replicas (each one engine —
plain or tensor-parallel) with the same asyncio `submit()`/`stream()`
surface a single frontend exposes, adding the scale-out policies:

* **Prefix-affinity dispatch.** Each replica's radix prefix cache only
  pays off when same-prefix requests LAND on it, so the router keeps a
  `ShadowRadixIndex` — a block-aligned token trie per replica,
  recording the prompts (and chat-turn outputs) it has dispatched
  there. A new request is routed to the replica whose shadow tree
  holds its longest cached-prefix estimate (>= one full KV block),
  ties and misses falling back to least-loaded. The shadow tree is an
  ESTIMATE — the replica may have evicted the blocks — but a stale hit
  only costs a normal prefill, never correctness.
* **Queue-depth load balancing.** Load per replica = frontend
  admission queue + engine FIFO + resident slots + router dispatches
  not yet admitted; exported per replica as
  `paddle_tpu_serving_router_replica_queue_depth`.
* **Health + lossless failover.** `ReplicaHealth` probes each
  frontend's step-loop task; dispatch skips dead replicas, and an
  in-flight stream races its token queue against the replica's down
  event. On a replica death the request re-submits elsewhere and the
  router suppresses the tokens the caller already received — prompts
  are re-prefillable, so nothing is lost; with greedy sampling the
  re-generated tokens are identical (sampled requests may diverge
  after a failover, same as any re-submission).

Everything is in-process asyncio (the CPU test harness runs 2+
replicas in one process); the replica boundary is the
`ServingFrontend` API, so a multi-host transport can slot in behind
the same router later.
"""
from __future__ import annotations

import asyncio
import heapq
import itertools

from ...profiler import metrics as _pmetrics
from .. import metrics as smetrics
from ..frontend import (DeadlineExceeded, FrontendClosed,
                        RequestCancelled)
from .health import ReplicaHealth


class NoReplicaAvailable(Exception):
    """Every replica is down (or none was configured)."""


class _ReplicaDied(Exception):
    """Internal: the dispatch replica died mid-stream (down event)."""


#: exceptions that MAY mean "the REPLICA failed", not "the REQUEST
#: failed": a stopped/crashed frontend (FrontendClosed) or an
#: engine/step-loop error (RuntimeError — e.g. a crashed mixed step;
#: the step loop fails every handle of that replica with it). The
#: router confirms with a health probe before failing over: a live
#: replica can raise RuntimeError for ONE request (the engine-stall
#: path fails the affected handles and keeps serving), and treating
#: that as replica death would let a single oversized request mark
#: every healthy replica down in turn.
_FAILOVER_ERRORS = (FrontendClosed, RuntimeError, _ReplicaDied)


class _ShadowNode:
    __slots__ = ("children", "stamp", "parent", "key")

    def __init__(self, stamp=0, parent=None, key=None):
        self.children = {}          # block token tuple -> _ShadowNode
        self.stamp = stamp
        self.parent = parent        # None once evicted (and for roots)
        self.key = key              # this node's chunk in parent.children


class ShadowRadixIndex:
    """Router-side estimate of each replica's radix prefix cache.

    One trie per replica over BLOCK-ALIGNED token chunks (the same
    granularity `serving.prefix_cache` caches at — partial tail blocks
    are never cached, so they never count toward affinity either).
    Bounded: beyond `capacity_blocks` nodes per replica, the
    oldest-stamped leaves are evicted — mirroring, approximately, the
    LRU the real cache applies under pool pressure."""

    def __init__(self, block_size, capacity_blocks=4096):
        self.bs = int(block_size)
        self.cap = int(capacity_blocks)
        self._roots = {}                   # replica -> _ShadowNode
        self._counts = {}                  # replica -> node count
        self._heaps = {}                   # replica -> [(stamp, seq, node)]
        self._tick = itertools.count(1)
        self._seq = itertools.count()      # heap tie-breaker

    def _chunks(self, tokens):
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + self.bs])
                for i in range(0, len(toks) - self.bs + 1, self.bs)]

    def match(self, replica, tokens):
        """Longest cached-prefix estimate, in TOKENS (block multiple)."""
        node = self._roots.get(replica)
        if node is None:
            return 0
        stamp = next(self._tick)
        n = 0
        for chunk in self._chunks(tokens):
            nxt = node.children.get(chunk)
            if nxt is None:
                break
            nxt.stamp = stamp             # touch: affinity reads keep
            n += self.bs                  # hot paths resident
            node = nxt
        if n and not node.children:
            # the touched tail is a leaf: record the fresh stamp in the
            # eviction heap so the touch actually protects it
            self._push(replica, node)
        return n

    def insert(self, replica, tokens):
        root = self._roots.get(replica)
        if root is None:
            root = self._roots[replica] = _ShadowNode()
            self._counts[replica] = 0
            self._heaps[replica] = []
        stamp = next(self._tick)
        node = root
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                child = node.children[chunk] = _ShadowNode(
                    stamp, node, chunk)
                self._counts[replica] += 1
            child.stamp = stamp
            node = child
        if node is not root and not node.children:
            self._push(replica, node)
        self._evict(replica)

    def drop(self, replica):
        """Forget a replica's whole tree (it died; its cache is gone)."""
        self._roots.pop(replica, None)
        self._counts.pop(replica, None)
        self._heaps.pop(replica, None)

    def size(self, replica):
        return self._counts.get(replica, 0)

    def _push(self, replica, node):
        heapq.heappush(self._heaps[replica],
                       (node.stamp, next(self._seq), node))

    def _evict(self, replica):
        # lazy-deletion min-heap over leaf stamps: every live leaf's
        # LATEST stamp has an entry (pushed on creation and on every
        # touch), so popping until a valid one is amortized O(log n)
        # per eviction — this runs on the per-request dispatch path,
        # where the old full-trie rescan per evicted leaf was O(cap)
        heap = self._heaps.get(replica)
        root = self._roots.get(replica)
        while self._counts.get(replica, 0) > self.cap and heap:
            stamp, _, node = heapq.heappop(heap)
            parent = node.parent
            if (node.stamp != stamp or node.children or parent is None
                    or parent.children.get(node.key) is not node):
                continue                  # stale entry: touched,
            del parent.children[node.key]  # re-parented or already gone
            node.parent = None
            self._counts[replica] -= 1
            if parent is not root and not parent.children:
                # the parent just became an evictable leaf
                self._push(replica, parent)


class ReplicaRouter:
    """Prefix-affinity dispatch over N serving frontends.

    Usage::

        router = ReplicaRouter([fe0, fe1])
        async with router:
            toks = await router.submit(prompt, max_new_tokens=32)
            async for tok in router.stream(prompt2, tenant="b"):
                ...

    `policy` is "affinity" (shadow-radix longest-prefix, falling back
    to least-loaded) or "round_robin" (the baseline the affinity
    contract in tools/router_smoke.py is measured against).
    """

    def __init__(self, frontends, *, policy="affinity",
                 shadow_capacity=4096, probe_interval=0.05):
        if not frontends:
            raise ValueError("ReplicaRouter needs at least one replica")
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown policy {policy!r}")
        self.frontends = list(frontends)
        self.policy = policy
        self.health = ReplicaHealth(self.frontends)
        bs = {fe.engine.block_size for fe in self.frontends}
        if len(bs) != 1:
            raise ValueError(
                f"replicas disagree on block_size: {sorted(bs)}")
        self.shadow = ShadowRadixIndex(bs.pop(),
                                       capacity_blocks=shadow_capacity)
        self.clock = self.frontends[0].engine.clock
        self.probe_interval = float(probe_interval)
        self._inflight = [0] * len(self.frontends)
        self._rr = itertools.count()
        self._prober = None
        # raw counters (always on; mirrored into the metrics registry
        # only when observability is enabled)
        self.dispatches = 0
        self.affinity_hits = 0
        self.failovers = 0

    # ---------------------------------------------------------- lifecycle
    async def start(self):
        for fe in self.frontends:
            await fe.start()
        if self._prober is None:
            self._prober = asyncio.get_running_loop().create_task(
                self.health.run(self.probe_interval))
        return self

    async def stop(self):
        if self._prober is not None:
            self._prober.cancel()
            try:
                await self._prober
            except asyncio.CancelledError:
                pass
            self._prober = None
        for i, fe in enumerate(self.frontends):
            if self.health.probe(i):
                await fe.stop()

    async def __aenter__(self):
        return await self.start()

    async def __aexit__(self, *exc):
        await self.stop()

    # ----------------------------------------------------------- dispatch
    def queue_depth(self, i):
        """The load the balancer compares: everything queued or
        resident on replica `i`, plus router dispatches in flight that
        its frontend may not have admitted yet."""
        fe = self.frontends[i]
        sch = fe.engine.scheduler
        return (len(fe._fair) + len(sch.queue) + sch.num_active
                + self._inflight[i])

    def _export_depths(self):
        if _pmetrics._enabled:
            for i in range(len(self.frontends)):
                smetrics.ROUTER_REPLICA_QUEUE_DEPTH.labels(str(i)).set(
                    self.queue_depth(i))

    def _pick(self, prompt):
        """(replica index, affinity_hit) for one dispatch. Raises
        NoReplicaAvailable when every replica is down."""
        live = [i for i in range(len(self.frontends))
                if self.health.alive(i)]
        if not live:
            raise NoReplicaAvailable(
                f"all {len(self.frontends)} replicas are down")
        self.dispatches += 1
        if self.policy == "round_robin":
            idx = live[next(self._rr) % len(live)]
            self.shadow.insert(idx, prompt)
            self._export_depths()
            return idx, False
        hits = {i: self.shadow.match(i, prompt) for i in live}
        best = max(hits.values())
        affinity = best >= self.shadow.bs        # >= one full KV block
        cands = [i for i in live if hits[i] == best] if affinity \
            else live
        idx = min(cands, key=lambda i: (self.queue_depth(i), i))
        if affinity:
            self.affinity_hits += 1
            if _pmetrics._enabled:
                smetrics.ROUTER_AFFINITY_HITS.inc()
        # record at DISPATCH time (not completion): concurrent requests
        # with the same head must converge on the same replica even
        # before the first one finishes prefill
        self.shadow.insert(idx, prompt)
        self._export_depths()
        return idx, affinity

    # ------------------------------------------------------------ serving
    async def submit(self, prompt, max_new_tokens=32, *,
                     tenant="default", timeout=None):
        """Run one request to completion (with transparent failover);
        returns its generated token ids."""
        out = []
        async for tok in self.stream(prompt, max_new_tokens,
                                     tenant=tenant, timeout=timeout):
            out.append(tok)
        return out

    async def stream(self, prompt, max_new_tokens=32, *,
                     tenant="default", timeout=None):
        """Async generator of generated tokens. On a replica death the
        request transparently re-submits to a live replica; tokens the
        caller already received are suppressed from the re-run."""
        deadline = (self.clock() + float(timeout)
                    if timeout is not None else None)
        delivered = 0
        while True:
            idx, _ = self._pick(prompt)
            remaining = None
            if deadline is not None:
                remaining = deadline - self.clock()
                if remaining <= 0:
                    self._count(idx, "expired")
                    raise DeadlineExceeded()
            # count the dispatch in the load estimate only until the
            # replica's frontend admits it into its fair queue — from
            # then on queue_depth sees it there (then in the engine
            # FIFO / resident slots), and keeping _inflight held for
            # the whole request would double-count every admitted
            # request against that replica
            self._inflight[idx] += 1
            pending = [True]

            def _admitted(idx=idx, pending=pending):
                if pending[0]:
                    pending[0] = False
                    self._inflight[idx] -= 1
                    self._export_depths()

            attempt_out = []
            try:
                async for tok in self._attempt(idx, prompt,
                                               max_new_tokens, tenant,
                                               remaining, attempt_out,
                                               _admitted):
                    if len(attempt_out) > delivered:
                        delivered += 1
                        yield tok
                # replica finished the request: publish the chat turn
                # to its shadow tree (the engine's finish-insert did
                # the same with the real blocks)
                self.shadow.insert(idx, list(prompt) + attempt_out)
                self._count(idx, "finished")
                return
            except _FAILOVER_ERRORS as e:
                if not isinstance(e, _ReplicaDied) \
                        and self.health.probe(idx):
                    # the replica is still serving: this was a
                    # per-REQUEST failure (e.g. the engine-stall
                    # RuntimeError for a working set its pool can't
                    # hold) — surface it; re-submitting the same
                    # request to identical replicas would just stall
                    # them one by one
                    self._count(idx, "error")
                    raise
                self.health.mark_down(idx)
                self.shadow.drop(idx)
                self.failovers += 1
                self._count(idx, "failover")
                if _pmetrics._enabled:
                    smetrics.ROUTER_FAILOVERS.inc()
                continue                      # re-dispatch elsewhere
            except DeadlineExceeded:
                self._count(idx, "expired")
                raise
            except RequestCancelled:
                self._count(idx, "cancelled")
                raise
            except Exception:
                self._count(idx, "error")
                raise
            finally:
                if pending[0]:
                    pending[0] = False
                    self._inflight[idx] -= 1
                self._export_depths()

    async def _attempt(self, idx, prompt, max_new_tokens, tenant,
                       timeout, attempt_out, on_admitted):
        """One dispatch to replica `idx`: forward its stream, racing
        the replica's down event (rescues requests stranded on a
        step-loop that died without failing its handles)."""
        fe = self.frontends[idx]
        q = asyncio.Queue()
        agen = fe.stream(prompt, max_new_tokens, tenant=tenant,
                         timeout=timeout, on_admitted=on_admitted)

        async def pump():
            try:
                async for tok in agen:
                    q.put_nowait(("tok", tok))
                q.put_nowait(("done", None))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                q.put_nowait(("err", e))

        async def watch():
            await self.health.down_event(idx).wait()
            q.put_nowait(("down", None))

        loop = asyncio.get_running_loop()
        tasks = (loop.create_task(pump()), loop.create_task(watch()))
        try:
            while True:
                kind, val = await q.get()
                if kind == "tok":
                    attempt_out.append(val)
                    yield val
                elif kind == "done":
                    return
                elif kind == "err":
                    raise val
                else:                          # down event fired
                    raise _ReplicaDied(f"replica {idx} died mid-stream")
        finally:
            # no awaits here: this finally also runs under GeneratorExit
            # when the caller abandons the stream. Cancelling the pump
            # closes fe.stream, whose own finally cancels the engine
            # request.
            for t in tasks:
                t.cancel()

    # ------------------------------------------------------------ helpers
    def _count(self, idx, outcome):
        if _pmetrics._enabled:
            smetrics.ROUTER_REQUESTS.labels(str(idx), outcome).inc()

    def stats(self):
        """Router-side counters (always on, registry-independent)."""
        return {"dispatches": self.dispatches,
                "affinity_hits": self.affinity_hits,
                "failovers": self.failovers,
                "health": self.health.snapshot(),
                "queue_depths": [self.queue_depth(i) for i in
                                 range(len(self.frontends))]}
