"""Block-granular KV transport between serving replicas.

Disaggregated prefill/decode serving (docs/SERVING.md, "Disaggregated
serving") moves a request's paged KV blocks between replicas: a
prefill-role replica streams the blocks it writes to a decode-role
replica as prefill chunks complete, hands the request off at the first
sampled token, and a loaded decode replica can later shed the live
request — blocks and all — to a sibling. The unit of transfer is the
PR 9 KV block: `[L, BS, H, Dh]` K/V payloads plus, for int8 pools, the
`[L, BS, H]` fp32 scale rows that share the block's coordinates — a
block is self-contained by construction, so shipping it preserves the
dequantization of every entry bit-exactly.

Three layers here:

* **Codec** — `encode_chunk`/`decode_chunk` and `encode_state`/
  `decode_state`: a versioned bytes-on-the-wire format (magic +
  length-prefixed JSON header describing geometry and array layout +
  raw C-order array payloads). Round-trips are bit-exact for
  fp32/bf16/int8 pools including scale rows (tests/test_transport.py
  property-tests this), and the header's `kv_meta` geometry lets the
  importer refuse a mismatched fleet instead of corrupting a pool.
* **`MigrationTicket`** — the request's host state (prompt, generated
  output, horizon, deadlines, timing for the metrics continuity) plus
  the block chunks not yet streamed. Everything the destination's
  `ServingEngine.submit_migrated` needs to resume the request
  token-identically under greedy decoding.
* **`KVTransport`** — the pluggable wire. `InProcessTransport` is the
  reference implementation: chunks and tickets pass through the real
  codec (`wire=True`, the default) so byte counts and bit-exactness
  are exercised on every transfer, landing in a per-(destination, key)
  inbox the router collects from. A multi-host transport implements
  the same five methods over a real fabric; everything above this
  module is already written against the interface.

Metrics: raw counters on the transport object (always on) are mirrored
into `paddle_tpu_serving_kv_transport_bytes_total{direction}` when the
profiler registry is enabled; block import counts ride
`kv_cache.PagedKVCache.blocks_imported` and surface as
`paddle_tpu_serving_kv_blocks_migrated_total` via the engine's step
mirror.
"""
from __future__ import annotations

import dataclasses
import json
import struct

import numpy as np

from ...profiler import metrics as _pmetrics

MAGIC = b"PTKV"
VERSION = 1


def _np_dtype(name):
    """np.dtype by name, with ml_dtypes (bfloat16 & friends) available
    — jax has registered them long before any transport runs, but the
    import keeps the codec usable standalone."""
    import ml_dtypes  # noqa: F401  (registers bfloat16 with numpy)
    return np.dtype(name)


@dataclasses.dataclass
class BlockChunk:
    """A contiguous run of a slot's KV blocks in transit.

    `start` indexes the slot's block TABLE (not the pool): chunk i of a
    request covers table entries [start, start+count). `arrays` is
    `(k, v)` for float pools or `(k, v, k_scale, v_scale)` for int8 —
    each `[count, L, BS, ...]`, exactly what
    `PagedKVCache.export_blocks` produced and `import_blocks` expects.
    """
    start: int
    count: int
    arrays: tuple

    @property
    def nbytes(self):
        return int(sum(a.nbytes for a in self.arrays))


#: host-state fields of a ticket, in wire order (everything except the
#: chunks, which travel as separate codec frames)
_STATE_FIELDS = ("prompt", "output", "max_new_tokens", "eos_token_id",
                 "deadline", "tenant", "slot_len", "total_blocks",
                 "kv_meta", "submit_time", "first_token_time",
                 "cache_hit_tokens", "preemptions", "created_at",
                 "adapter_id", "trace_id")


@dataclasses.dataclass
class MigrationTicket:
    """Everything a destination engine needs to resume a live request.

    Built by `ServingEngine.extract_request`; consumed by
    `ServingEngine.submit_migrated`. `chunks` holds the blocks NOT yet
    streamed ahead (for a prefill handoff the tail past
    `Request.shipped_blocks`; for a decode shed, everything); the
    router's transport merges pre-streamed chunks back in, and
    `total_blocks` lets the importer validate full coverage before it
    touches a pool. Timing fields carry over so TTFT is observed once
    and inter-token gaps stay continuous across the migration.
    """
    prompt: list
    output: list
    max_new_tokens: int
    eos_token_id: object
    deadline: object
    tenant: str
    slot_len: int
    total_blocks: int
    kv_meta: dict
    chunks: list
    submit_time: float = 0.0
    first_token_time: object = None
    cache_hit_tokens: int = 0
    preemptions: int = 0
    created_at: float = 0.0
    # multi-LoRA (serving.adapters): the adapter the request decodes
    # under travels with it — the destination re-acquires a slot pin
    # at admission (it must hold the registration; JSON-serializable
    # ids only, like tenant)
    adapter_id: object = None
    # fleet-wide request tracing (serving.tracing, ISSUE 16): the trace
    # id travels on the wire with the host state, so the destination's
    # scheduler stitches its spans onto the SAME trace the source and
    # router were writing (a JSON-safe string, None = source not
    # tracing)
    trace_id: object = None

    def state_dict(self):
        d = {f: getattr(self, f) for f in _STATE_FIELDS}
        d["prompt"] = [int(t) for t in self.prompt]
        d["output"] = [int(t) for t in self.output]
        return d


# --------------------------------------------------------------- codec
def _frame(header: dict, payloads) -> bytes:
    hj = json.dumps(header).encode("utf-8")
    parts = [MAGIC, struct.pack("<I", len(hj)), hj]
    parts.extend(payloads)
    return b"".join(parts)


def _unframe(data: bytes):
    if data[:4] != MAGIC:
        raise ValueError("not a PTKV frame (bad magic)")
    (hlen,) = struct.unpack_from("<I", data, 4)
    header = json.loads(data[8:8 + hlen].decode("utf-8"))
    if header.get("v") != VERSION:
        raise ValueError(f"unsupported PTKV version {header.get('v')}")
    return header, 8 + hlen


def encode_chunk(meta: dict, chunk: BlockChunk) -> bytes:
    """One block chunk -> wire bytes: header (geometry + per-array
    dtype/shape) + raw C-order payloads. Bit-exact by construction —
    `tobytes()`/`frombuffer` never reinterpret values."""
    header = {
        "v": VERSION, "kind": "chunk", "meta": dict(meta),
        "start": int(chunk.start), "count": int(chunk.count),
        "arrays": [{"dtype": str(a.dtype), "shape": list(a.shape)}
                   for a in chunk.arrays],
    }
    payloads = [np.ascontiguousarray(a).tobytes() for a in chunk.arrays]
    return _frame(header, payloads)


def decode_chunk(data: bytes):
    """Wire bytes -> (meta, BlockChunk). Arrays are fresh host copies
    (writable), so the importer can pad/concatenate freely."""
    header, off = _unframe(data)
    if header.get("kind") != "chunk":
        raise ValueError(f"expected a chunk frame, got {header.get('kind')!r}")
    arrays = []
    for desc in header["arrays"]:
        dt = _np_dtype(desc["dtype"])
        shape = tuple(desc["shape"])
        n = int(np.prod(shape)) if shape else 1
        a = np.frombuffer(data, dtype=dt, count=n, offset=off)
        arrays.append(a.reshape(shape).copy())
        off += n * dt.itemsize
    return header["meta"], BlockChunk(start=int(header["start"]),
                                      count=int(header["count"]),
                                      arrays=tuple(arrays))


def encode_state(ticket: MigrationTicket) -> bytes:
    header = {"v": VERSION, "kind": "state", "state": ticket.state_dict()}
    return _frame(header, [])


def decode_state(data: bytes) -> dict:
    header, _ = _unframe(data)
    if header.get("kind") != "state":
        raise ValueError(f"expected a state frame, got {header.get('kind')!r}")
    return header["state"]


# ----------------------------------------------------------- transport
class KVTransport:
    """Pluggable block-granular transport between replicas.

    `send_chunk` ships one `BlockChunk` toward `(dst, key)` — the
    prefill-streaming path; `send_ticket` ships a ticket's host state
    plus its remaining chunks — the handoff/shed path; `collect` pops
    the assembled ticket at the destination; `pending`/`drop` manage
    abandoned transfers. Raw byte counters are always on; the registry
    mirror records only when profiler metrics are enabled.
    """

    def __init__(self):
        self.bytes_sent = 0
        self.bytes_received = 0
        self.blocks_sent = 0
        self.tickets_sent = 0

    def _note(self, sent, received):
        self.bytes_sent += int(sent)
        self.bytes_received += int(received)
        if _pmetrics._enabled:
            from .. import metrics as smetrics
            smetrics.SERVING_KV_TRANSPORT_BYTES.labels("sent").inc(sent)
            smetrics.SERVING_KV_TRANSPORT_BYTES.labels("received").inc(
                received)

    # one chunk toward (dst, key); meta is the source pool's kv_meta()
    def send_chunk(self, src, dst, key, meta, chunk):
        raise NotImplementedError

    # ticket state + its unstreamed chunks toward (dst, key)
    def send_ticket(self, src, dst, key, ticket):
        raise NotImplementedError

    # assembled MigrationTicket at dst (state + every chunk, in table
    # order); raises KeyError when the state frame has not arrived
    def collect(self, dst, key):
        raise NotImplementedError

    def pending(self, dst, key):
        raise NotImplementedError

    # forget a transfer (request finished/cancelled before handoff)
    def drop(self, dst, key):
        raise NotImplementedError


class InProcessTransport(KVTransport):
    """Reference transport: same-process inbox, REAL codec on the wire.

    With `wire=True` (default) every chunk and ticket is encoded to
    bytes and decoded back, so byte accounting, geometry validation and
    bit-exactness are exercised on every transfer exactly as a network
    transport would; `wire=False` passes arrays through zero-copy
    (bytes counted analytically) for tests that isolate the transport
    interface from the codec."""

    def __init__(self, wire=True):
        super().__init__()
        self.wire = bool(wire)
        self._inbox = {}            # (dst, key) -> {"state", "chunks"}

    def _box(self, dst, key):
        return self._inbox.setdefault((dst, key),
                                      {"state": None, "chunks": []})

    def send_chunk(self, src, dst, key, meta, chunk):
        if self.wire:
            data = encode_chunk(meta, chunk)
            self._note(len(data), len(data))
            meta, chunk = decode_chunk(data)
        else:
            nb = chunk.nbytes
            self._note(nb, nb)
        self.blocks_sent += chunk.count
        self._box(dst, key)["chunks"].append(chunk)

    def send_ticket(self, src, dst, key, ticket):
        nb0 = self.bytes_sent
        for chunk in ticket.chunks:
            self.send_chunk(src, dst, key, ticket.kv_meta, chunk)
        if self.wire:
            data = encode_state(ticket)
            self._note(len(data), len(data))
            state = decode_state(data)
        else:
            state = ticket.state_dict()
            self._note(64, 64)       # nominal host-state frame
        self.tickets_sent += 1
        self._box(dst, key)["state"] = state
        from .. import tracing as _tracing
        if _tracing._enabled:
            _tracing.on_transport(
                getattr(ticket, "trace_id", None), src, dst,
                nbytes=self.bytes_sent - nb0,
                blocks=sum(c.count for c in ticket.chunks))

    def collect(self, dst, key):
        box = self._inbox.pop((dst, key), None)
        if box is None or box["state"] is None:
            raise KeyError(f"no complete ticket for ({dst!r}, {key!r})")
        chunks = sorted(box["chunks"], key=lambda c: c.start)
        return MigrationTicket(chunks=chunks, **box["state"])

    def pending(self, dst, key):
        return (dst, key) in self._inbox

    def drop(self, dst, key):
        self._inbox.pop((dst, key), None)
