"""Replica health tracking for the multi-replica router.

A replica is one `ServingFrontend` (its background step-loop task
drives one engine). Health here is deliberately simple and fully
in-process: a replica is DISPATCHABLE while its step-loop task exists,
has not finished, and the frontend has not been closed — exactly the
conditions under which a submitted request will eventually be served.
A step-loop that died on an engine exception, a frontend that was
stopped, or a task that was cancelled outright all probe as down.

Two consumers:

* the router's dispatch path calls `alive(i)` synchronously per
  request, so a death is noticed at the very next dispatch even
  between prober ticks;
* the async prober (`run()`) sweeps every `interval` seconds and fires
  the per-replica `down_event` — the router's in-flight streams wait
  on that event alongside their token queue, which is what rescues
  requests stranded on a replica that died WITHOUT failing its
  handles (e.g. a hard-cancelled task).
"""
from __future__ import annotations

import asyncio

from ..metrics import ROUTER_REPLICAS_UP


class ReplicaHealth:
    def __init__(self, frontends):
        self.frontends = list(frontends)
        n = len(self.frontends)
        self._down = [False] * n
        self._events = [None] * n      # created lazily (need a loop)
        self.probes = 0

    # ----------------------------------------------------------- state
    def __len__(self):
        return len(self.frontends)

    def add(self, frontend):
        """Track one more replica (fleet scale-up, ISSUE 17): indices
        are append-only — a retired replica keeps its slot marked down
        forever, so in-flight streams' down-event watchers stay valid.
        Returns the new replica's index."""
        self.frontends.append(frontend)
        self._down.append(False)
        self._events.append(None)
        self._export()
        return len(self.frontends) - 1

    def probe(self, i):
        """True when replica `i`'s step loop is running right now."""
        self.probes += 1
        fe = self.frontends[i]
        task = fe._task
        return (not fe._closed and task is not None
                and not task.done())

    def alive(self, i):
        """Dispatchable: not marked down AND probing healthy. A failed
        probe marks the replica down as a side effect, so dispatch
        never races the async prober."""
        if self._down[i]:
            return False
        if not self.probe(i):
            self.mark_down(i)
            return False
        return True

    @property
    def num_up(self):
        return sum(self.alive(i) for i in range(len(self.frontends)))

    def mark_down(self, i):
        if not self._down[i]:
            self._down[i] = True
            ev = self._events[i]
            if ev is not None:
                ev.set()
        self._export()

    def mark_up(self, i):
        """Manual revive (a restarted frontend re-enters rotation)."""
        self._down[i] = False
        ev = self._events[i]
        if ev is not None:
            # clear the SAME Event object rather than discarding it:
            # in-flight streams' watchers hold a reference, and a
            # fresh Event would orphan them — a later death would fire
            # the replacement while they wait forever on the old one
            ev.clear()
        self._export()

    def down_event(self, i):
        """The asyncio.Event fired when replica `i` goes down; router
        streams race it against their token queue."""
        ev = self._events[i]
        if ev is None:
            ev = self._events[i] = asyncio.Event()
            if self._down[i]:
                ev.set()
        return ev

    def snapshot(self):
        return {"up": [i for i in range(len(self.frontends))
                       if not self._down[i]],
                "down": [i for i, d in enumerate(self._down) if d],
                "probes": self.probes}

    def _export(self):
        ROUTER_REPLICAS_UP.set(
            sum(1 for d in self._down if not d))

    # ---------------------------------------------------------- prober
    async def run(self, interval=0.05):
        """Background sweep: fire down events for replicas whose step
        loop died without failing its handles. Cancelled by the router
        on stop."""
        while True:
            for i in range(len(self.frontends)):
                if not self._down[i] and not self.probe(i):
                    self.mark_down(i)
            await asyncio.sleep(interval)
