"""Tensor-parallel (x expert-parallel) serving engine: the ONE mixed
step, sharded.

`TPServingEngine` runs the exact host loop of `serving.engine`
(scheduler, paged KV bookkeeping, speculation, prefix cache — all
inherited unchanged) while the compiled mixed step executes SPMD over
a 1-D `("mp",)` mesh (`parallel.mp_layers.tp_mesh`) — or, for MoE
decoder stacks, a 2-D `("ep", "mp")` mesh (`mp_layers.tp_ep_mesh`):

* **Heads partitioned on `mp`** — the fused QKV out axis is permuted
  host-side into shard-major order (`mp_layers.shard_major_qkv`) so a
  plain `P(..., "mp")` sharding IS a head split; each shard's step
  body runs `_qkv`/attention with `cfg.num_heads = H // tp` and the
  `ops.pallas.flash_attention` ragged/verify/paged entries see
  per-shard head slices of q and of the pools.
* **KV block pools sharded on the head axis** — `[L, NB, BS, H, Dh]`
  pools carry `P(None, None, None, "mp")`, so each chip holds
  `1/tp` of the KV bytes; block TABLES stay replicated host-side
  numpy exactly as in the single-chip engine (identical block ids on
  every shard — the allocator remains one logical free list).
* **Row-parallel reductions in the body** — the attention out
  projection and ffn2 each hold a head/ff shard of their IN axis; the
  shared `_step_body` (engine.py) emits `lax.psum(..., "mp")` for both
  via `cfg.mp_axis`, after which hidden states are replicated and the
  sampling head runs identically on every shard.
* **Experts partitioned on `ep`** (`expert_parallel > 1`, MoE stacks
  only) — the expert-stacked FFN weights shard their expert axis over
  `ep` (`mp_layers.SERVING_MOE_TP_SPECS`) while each expert's FFN
  keeps the dense column/row mp split, so TP and EP COMPOSE. The token
  set is replicated across shards, so the training-style all_to_all
  degenerates: each shard slices its resident experts out of the
  (identical) `[E, C, D]` dispatch tensor, runs `E/ep` experts at
  capacity `C`, and the combine psums partial mixtures over `ep`
  (`incubate.nn.fused_transformer._ffn_moe_tokens`). Routing, gate
  logits and the MoE statistics are identical on every shard, so
  EP=2 serving is token-identical to EP=1 — same one-compile rule,
  capacity overflow still degrades to the residual path. KV pools
  replicate over `ep` (they shard over `mp` only).

Contracts (tests/test_tp_serving.py + tests/test_moe.py): token parity
with the TP=1/EP=1 engine on the CPU virtual-device mesh (speculation
on and off), still exactly ONE compile per engine, allocator/CoW/
truncate/prefix-cache invariants unchanged per shard.
"""
from __future__ import annotations

from ...parallel import shard_map as _shard_map
from ...parallel.mp_layers import (serving_tp_spec, shard_major_qkv,
                                   tp_ep_mesh, tp_mesh)
from ..engine import ServingEngine


class TPServingEngine(ServingEngine):
    """`ServingEngine` with the mixed step sharded over an `mp` (or
    `ep x mp` for MoE) mesh.

    `tensor_parallel=1` degrades to a 1-device mesh (useful for
    exercising the shard_map plumbing without parallelism);
    `expert_parallel > 1` shards a MoE stack's experts over the extra
    `ep` mesh rows. The host API is identical to the base engine.

    Device-resident multi-tick decode (ISSUE 18) composes for free:
    the base engine wraps the RESULT of `_build_step()` — here the
    shard_map'ed body — in its `lax.while_loop`, so the loop sits
    OUTSIDE the mesh partitioning and the control tail (n_ticks/eos/
    remain/cap[/slot_ad][/draft ring + counts]) rides as replicated
    host inputs like the flat-token data args. On-device speculation
    (ISSUE 19) inherits the same way: the loop's drafter/accept/ring
    math runs on replicated inputs outside shard_map, so a TP=2 spec
    engine traces the IDENTICAL drafter as TP=1. Token identity vs
    N=1 at TP=2 and the one-compile budget are asserted by
    tests/test_multitick.py.
    """

    def __init__(self, model, *, tensor_parallel=2, expert_parallel=1,
                 mesh=None, **kw):
        dec = model.decoder
        tp = int(tensor_parallel)
        ep = int(expert_parallel)
        n_exp = int(getattr(dec, "_num_experts", 0))
        if ep > 1 and not n_exp:
            raise ValueError(
                "expert_parallel > 1 needs a MoE decoder stack "
                "(FusedMultiTransformerMoe)")
        if n_exp and n_exp % ep:
            raise ValueError(
                f"num_experts={n_exp} not divisible by "
                f"expert_parallel={ep}")
        if dec.num_heads % tp:
            raise ValueError(
                f"num_heads={dec.num_heads} not divisible by "
                f"tensor_parallel={tp}")
        if dec.dim_feedforward % tp:
            raise ValueError(
                f"dim_feedforward={dec.dim_feedforward} not divisible "
                f"by tensor_parallel={tp}")
        self.tensor_parallel = tp
        self.expert_parallel = ep
        # MoE stacks always ride the 2-D mesh (the expert param specs
        # name "ep" even at ep=1); dense stacks keep the 1-D mesh the
        # PR 8 contracts pinned
        if mesh is not None:
            self.mesh = mesh
        elif n_exp:
            self.mesh = tp_ep_mesh(tp, ep)
        else:
            self.mesh = tp_mesh(tp)
        want = ("ep", "mp") if n_exp else ("mp",)
        if tuple(self.mesh.axis_names) != want:
            raise ValueError(
                f"serving mesh for this stack must be {want}, got "
                f"{self.mesh.axis_names}")
        super().__init__(model, **kw)
        self._shard_state()

    def _flight_extra(self):
        # the mesh split rides every flight-recorder step record, so a
        # merged fleet chrome trace tells a TP=2/EP=2 replica's step
        # slices from a single-chip sibling's at a glance
        return {"tp": self.tensor_parallel, "ep": self.expert_parallel}

    # ------------------------------------------------------- sharding
    def _pool_spec(self):
        # head axis (index 3) of the [L, NB, BS, H, Dh] pools, in the
        # CANONICAL normal form (analysis.specs): the jit cache keys on
        # input shardings, so the spec the initial device_put places
        # the pools with must be byte-identical to the spec the step's
        # outputs carry — trailing Nones trimmed (the PR 8 lesson) and
        # the size-1 "mp" entry dropped to P() at tp=1 (the PR 10
        # EP-only-mesh lesson, caught by tools/moe_smoke.py) — or the
        # SECOND step pays a silent full recompile. canonicalize_spec
        # is the one shared definition of that form (the recompile-
        # hazard lint rule RH201/RH202 checks against the same logic).
        # Under the 2-D MoE mesh the same spec replicates over ep.
        from jax.sharding import PartitionSpec as P

        from ...analysis.specs import canonicalize_spec
        return canonicalize_spec(P(None, None, None, "mp"), self.mesh)

    def _summary_spec(self):
        # the block-summary pools (ISSUE 15) are [L, NB, H, Dh]: the
        # head axis sits at index 2, one spot earlier than in the
        # [L, NB, BS, H, Dh] payload pools — same canonical-form
        # discipline as _pool_spec
        from jax.sharding import PartitionSpec as P

        from ...analysis.specs import canonicalize_spec
        return canonicalize_spec(P(None, None, "mp"), self.mesh)

    def _array_specs(self):
        """One PartitionSpec per entry of `self._arrays` (the order
        `_gen_tensors` fixes: we, pe, decoder params, ln_f w/b, head —
        embeddings and the lm head replicate; decoder params follow
        `mp_layers.SERVING_TP_SPECS`, MoE experts
        `SERVING_MOE_TP_SPECS`). The ENGINE's name list is the source
        of truth: engine-side expert quantization may have added
        ffn1_s/ffn2_s entries the float model never had."""
        from jax.sharding import PartitionSpec as P
        names = self._names
        moe = self.num_experts > 0
        return ([P(), P()]
                + [serving_tp_spec(n, moe=moe)[0] for n in names]
                + [P(), P(), P()])

    def _adapter_specs(self):
        """PartitionSpec per adapter slot tensor, in
        `AdapterCache.array_names` order (SERVING_LORA_TP_SPECS)."""
        return [serving_tp_spec(n)[0]
                for n in self.adapters.array_names]

    def _shard_state(self):
        """Re-lay out the cast param arrays (shard-major QKV) and
        device_put params + KV pools + adapter slot tensors to their
        mesh shardings, so the first step call compiles against the
        final layouts and never pays a resharding copy."""
        import jax
        from jax.sharding import NamedSharding

        from ...analysis.specs import canonicalize_spec

        dec = self.model.decoder
        names = self._names
        H, Dh = dec.num_heads, dec.head_dim
        moe = self.num_experts > 0
        specs = self._array_specs()
        permute = ([False, False]
                   + [serving_tp_spec(n, moe=moe)[1] for n in names]
                   + [False, False, False])
        out = []
        for arr, spec, perm in zip(self._arrays, specs, permute):
            if perm:
                arr = shard_major_qkv(arr, self.tensor_parallel, H, Dh)
            out.append(jax.device_put(
                arr, NamedSharding(self.mesh, spec)))
        self._arrays = out
        psh = NamedSharding(self.mesh, self._pool_spec())
        ssh = NamedSharding(self.mesh, self._summary_spec())

        def _place(kv, _psh=psh, _ssh=ssh, _put=jax.device_put):
            kv.k_pool = _put(kv.k_pool, _psh)
            kv.v_pool = _put(kv.v_pool, _psh)
            if kv.quantized:
                # the [L, NB, BS, H] scale pools shard on the same
                # (head) axis — trailing-None-trimmed, P(None, None,
                # None, "mp") happens to be the pool spec verbatim
                kv.k_scale = _put(kv.k_scale, _psh)
                kv.v_scale = _put(kv.v_scale, _psh)
            if kv.summaries:
                # [L, NB, H, Dh] summary pools: head axis at index 2
                kv.k_sum_min = _put(kv.k_sum_min, _ssh)
                kv.k_sum_max = _put(kv.k_sum_max, _ssh)

        _place(self.kv)
        # KV block transport (disaggregated serving): imported pools
        # come out of the scatter executable with whatever sharding
        # GSPMD inferred — re-pin the canonical spec so the next mixed
        # step's input shardings stay byte-identical (a drift here is
        # a silent full recompile, the PR 8/PR 10 lesson)
        self.kv.place_pools = _place
        if self.adapters is not None:
            # adapter slot tensors: column-parallel B shards its out
            # axis (qkv's shard-major-permuted), row-parallel A its in
            # axis — the engine's step body then adds each delta on
            # the same side of the psum as its base matmul
            ad_sharding = {
                n: NamedSharding(self.mesh, canonicalize_spec(
                    spec, self.mesh))
                for n, spec in zip(self.adapters.array_names,
                                   self._adapter_specs())}
            for n in self.adapters.array_names:
                self.adapters._arrays[n] = jax.device_put(
                    self.adapters._arrays[n], ad_sharding[n])
            tp = self.tensor_parallel

            def _prepare(name, arr, _tp=tp, _H=H, _Dh=Dh):
                # host payload re-layout before the slot write: qkv's
                # B out axis must be shard-major like qkv_w so a plain
                # "mp" split IS a head split
                if serving_tp_spec(name)[1]:
                    import numpy as _np
                    return _np.asarray(shard_major_qkv(
                        jax.numpy.asarray(arr), _tp, _H, _Dh))
                return arr

            def _place_adapters(cache, _sh=ad_sharding,
                                _put=jax.device_put):
                # the donated load write's outputs re-pin the
                # canonical shardings (same lesson as place_pools)
                for n in cache.array_names:
                    cache._arrays[n] = _put(cache._arrays[n], _sh[n])

            self.adapters.prepare = _prepare
            self.adapters.place = _place_adapters

    # ------------------------------------------------- fleet weight swap
    def _prep_swap_arrays(self, arrays):
        """TP staging for `swap_weights` (ISSUE 17): the canonical
        model-order checkpoint gets the SAME host-side shard-major QKV
        permute `_shard_state` applies, so a plain "mp" split of the
        swapped arrays is still a head split. Shapes are unchanged —
        the shape gate in `swap_weights` still compares canonically."""
        import jax.numpy as jnp
        import numpy as np

        dec = self.model.decoder
        H, Dh = dec.num_heads, dec.head_dim
        moe = self.num_experts > 0
        permute = ([False, False]
                   + [serving_tp_spec(n, moe=moe)[1]
                      for n in self._names]
                   + [False, False, False])
        out = []
        for arr, perm in zip(arrays, permute):
            if perm:
                arr = np.asarray(shard_major_qkv(
                    jnp.asarray(arr), self.tensor_parallel, H, Dh))
            out.append(np.asarray(arr))
        return out

    def _swap_jit_kwargs(self):
        """Pin the swap cast's outputs to the step's param shardings:
        the jit cache keys on input shardings, so swapped arrays must
        come out byte-identical to what `_shard_state` placed — or the
        next mixed step would pay a silent full recompile (the PR 8
        lesson, applied to upgrades)."""
        from jax.sharding import NamedSharding
        return {"out_shardings": [
            NamedSharding(self.mesh, spec)
            for spec in self._array_specs()]}

    # ------------------------------------------------------ mixed step
    def _step_cfg(self):
        """Per-shard decoder config: local head count + the psum axis
        (engine._step_body emits the row-parallel reductions off it);
        MoE stacks additionally carry the ep axis/size for the
        slice-dispatch + psum-combine in `_ffn_moe_tokens`. Starts
        from the base engine's cfg so engine-side expert quantization
        (moe_quant_bits) composes with sharding."""
        import dataclasses
        cfg = ServingEngine._step_cfg(self)
        rep = dict(num_heads=cfg.num_heads // self.tensor_parallel,
                   mp_axis="mp")
        if self.num_experts:
            rep.update(ep_axis="ep", ep_size=self.expert_parallel)
        return dataclasses.replace(cfg, **rep)

    def _build_step(self):
        from jax.sharding import PartitionSpec as P

        from .. import batcher

        from ...analysis.specs import canonicalize_spec

        body = self._step_body(self._step_cfg())
        pool = self._pool_spec()
        rep = P()
        # quantized pools ride (k_scale, v_scale) right after the
        # pools, sharded on the same head axis; summary-tracking pools
        # add (k_sum_min, k_sum_max) after those with the head axis
        # one spot earlier — the kv_cache._pools() order; the step
        # returns them all
        pools = (pool,) * (4 if self.kv.quantized else 2)
        if self.kv.summaries:
            pools += (self._summary_spec(),) * 2
        # adapter slot tensors follow the pools (engine._step_body's
        # rest-parse order), each under its SERVING_LORA_TP_SPECS
        # sharding; the per-token adapter-id vector replicates with
        # the other flat-token inputs
        lora_in = tuple(
            canonicalize_spec(s, self.mesh)
            for s in self._adapter_specs()) \
            if self.adapters is not None else ()
        # flat-token inputs, block tables, the optional logit-processor
        # count histogram (ISSUE 19: the [S, Vb] device-updatable form
        # of the old history window) and the rng key replicate; sampled
        # tokens come off the replicated post-psum hidden state so the
        # token outputs replicate too (check_vma=False: 0.4.x's checker
        # can't see through the scanned psum)
        n_data = 6 + (1 if self.adapters is not None else 0) \
            + (1 if batcher.needs_history(self.sampling) else 0)
        data_in = (rep,) * n_data
        # spec-sampling adds the residual-resample + accept matrices
        # to the verify outputs (engine._step_body) — all replicated,
        # like the token outputs
        if self.draft_k:
            tok_out = (rep,) * (4 if self.spec_sampling else 2)
        else:
            tok_out = rep
        # MoE stats (counts/dropped/aux) come off replicated routing
        # inputs, identical on every shard
        stats_out = ({"counts": rep, "dropped": rep, "aux": rep},) \
            if self.num_experts else ()
        return _shard_map(
            body, mesh=self.mesh,
            in_specs=(self._array_specs(),) + pools + lora_in + data_in,
            out_specs=(tok_out,) + pools + stats_out, check_vma=False)
