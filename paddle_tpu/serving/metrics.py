"""Serving metrics — registered in the framework-wide PR 1 registry.

Exported names are part of the observability contract
(docs/SERVING.md, tools/serving_smoke.py greps them the same way
tools/metrics_dump.py greps the training-side names). Recording
follows the hot-path discipline: the engine records only when
`profiler.metrics._enabled` is on, so a serving loop with
observability off pays one branch per step.
"""
from __future__ import annotations

from ..profiler.metrics import (REGISTRY, exponential_buckets,
                                COMPILE_WATCHDOG_BUDGET_EXCEEDED,
                                MOE_AUX_LOSS, MOE_DROPPED_TOKENS,
                                MOE_EXPERT_TOKENS,
                                MOE_EXPERT_UTILIZATION,
                                TRANSFER_GUARD_TRIPS)  # noqa: F401
# (the MoE routing metrics live in profiler.metrics because the hybrid
# trainer records them too, and the ISSUE 12 guard counters because
# analysis.guards watches TRAINING jits as much as serving ones —
# re-exported here so the serving contract below registers them by
# import, like every other serving metric)

# 100us .. ~100s in x4 steps: TTFT on a loaded queue can sit behind
# whole prefill rounds, far above the dispatch-scale default buckets
_LATENCY_BUCKETS = exponential_buckets(1e-4, 4.0, 10)

SERVING_TTFT_SECONDS = REGISTRY.histogram(
    "paddle_tpu_serving_ttft_seconds",
    "Submit-to-first-token latency per request",
    buckets=_LATENCY_BUCKETS)
SERVING_INTER_TOKEN_SECONDS = REGISTRY.histogram(
    "paddle_tpu_serving_inter_token_seconds",
    "Gap between consecutive generated tokens of one request",
    buckets=_LATENCY_BUCKETS)
SERVING_QUEUE_DEPTH = REGISTRY.gauge(
    "paddle_tpu_serving_queue_depth",
    "Requests waiting for a slot (admission queue length)")
SERVING_ACTIVE_SLOTS = REGISTRY.gauge(
    "paddle_tpu_serving_active_slots",
    "Slots holding a resident (prefill or decode) request")
SERVING_KV_BLOCKS_IN_USE = REGISTRY.gauge(
    "paddle_tpu_serving_kv_blocks_in_use",
    "Allocated KV-cache blocks")
SERVING_KV_BLOCK_UTILIZATION = REGISTRY.gauge(
    "paddle_tpu_serving_kv_block_utilization",
    "Allocated fraction of the allocatable KV block pool")
SERVING_KV_BYTES_PER_TOKEN = REGISTRY.gauge(
    "paddle_tpu_serving_kv_bytes_per_token",
    "HBM bytes one cached token costs across K+V and all layers "
    "(int8 pools include their per-entry-per-head fp32 scales)")
SERVING_PREEMPTIONS = REGISTRY.counter(
    "paddle_tpu_serving_preemptions_total",
    "Decode requests evicted (blocks reclaimed, request requeued)")
SERVING_REQUESTS = REGISTRY.counter(
    "paddle_tpu_serving_requests_total",
    "Requests by terminal outcome",
    ("outcome",))   # finished|expired|cancelled
SERVING_TOKENS = REGISTRY.counter(
    "paddle_tpu_serving_tokens_total",
    "Tokens processed by the mixed step", ("kind",))  # prefill|decode
SERVING_STEPS = REGISTRY.counter(
    "paddle_tpu_serving_steps_total",
    "Mixed-step invocations")

# ---- radix prefix cache (prefix_caching=True) --------------------------
SERVING_PREFIX_HIT_TOKENS = REGISTRY.counter(
    "paddle_tpu_serving_prefix_cache_hit_tokens_total",
    "Prompt tokens whose KV was served from the radix prefix cache "
    "(never re-prefilled)")
SERVING_PREFIX_MISS_TOKENS = REGISTRY.counter(
    "paddle_tpu_serving_prefix_cache_miss_tokens_total",
    "Prompt tokens that had to be prefilled (no cached prefix)")
SERVING_PREFIX_EVICTIONS = REGISTRY.counter(
    "paddle_tpu_serving_prefix_cache_evictions_total",
    "Cached KV blocks reclaimed by LRU eviction under pool pressure")

# ---- block-sparse paged decode attention (ISSUE 15) --------------------
SERVING_KV_BLOCKS_SKIPPED = REGISTRY.counter(
    "paddle_tpu_serving_kv_blocks_skipped_total",
    "Candidate KV blocks the sparse decode path did NOT read (summary "
    "scoring kept a fixed top-B + sink + recency budget instead)")
SERVING_SPARSE_ATTENTION_RATIO = REGISTRY.gauge(
    "paddle_tpu_serving_sparse_attention_ratio",
    "Cumulative fraction of candidate KV blocks the sparse decode "
    "path actually attended (1.0 = dense; lower = sparser)")

# ---- disaggregated serving (serving.distributed.transport) -------------
SERVING_KV_BLOCKS_MIGRATED = REGISTRY.counter(
    "paddle_tpu_serving_kv_blocks_migrated_total",
    "KV blocks imported into a replica's pool from a prefill handoff "
    "or a load-shedding migration (int8 scale rows ride along)")
SERVING_KV_TRANSPORT_BYTES = REGISTRY.counter(
    "paddle_tpu_serving_kv_transport_bytes_total",
    "Bytes moved by the KV block transport (codec frames: headers + "
    "K/V payloads + scale rows + ticket state)",
    ("direction",))   # sent|received
SERVING_HANDOFF_LATENCY = REGISTRY.histogram(
    "paddle_tpu_serving_handoff_latency_seconds",
    "Stream gap a migration causes: ticket extraction on the source "
    "to the first token emitted by the destination replica",
    buckets=exponential_buckets(1e-4, 4.0, 10))

# ---- multi-LoRA adapter cache (serving.adapters, ISSUE 14) -------------
SERVING_ADAPTER_CACHE_HITS = REGISTRY.counter(
    "paddle_tpu_serving_adapter_cache_hits_total",
    "Admissions whose adapter was already resident in a device slot")
SERVING_ADAPTER_CACHE_MISSES = REGISTRY.counter(
    "paddle_tpu_serving_adapter_cache_misses_total",
    "Admissions that loaded a cold adapter into a device slot (one "
    "donated jitted slot-write each — never a recompile)")
SERVING_ADAPTER_EVICTIONS = REGISTRY.counter(
    "paddle_tpu_serving_adapter_evictions_total",
    "Resident adapters LRU-evicted from their slot to admit a cold one")
SERVING_ADAPTER_LOAD_SECONDS = REGISTRY.counter(
    "paddle_tpu_serving_adapter_load_seconds_total",
    "Wall seconds spent in adapter slot-write loads")
SERVING_ADAPTERS_RESIDENT = REGISTRY.gauge(
    "paddle_tpu_serving_adapters_resident",
    "Non-null adapters currently holding a device slot")

# ---- multi-replica router (serving.distributed.router) -----------------
ROUTER_REQUESTS = REGISTRY.counter(
    "paddle_tpu_serving_router_requests_total",
    "Router dispatches by replica, outcome and the serving replica's "
    "checkpoint version (ISSUE 17: a rolling upgrade is observable "
    "as the version label migrating across the fleet)",
    ("replica", "outcome", "version"))
# outcomes: finished|failover|expired|cancelled|error|migrated
ROUTER_MIGRATIONS = REGISTRY.counter(
    "paddle_tpu_serving_router_migrations_total",
    "Live-request migrations the router orchestrated",
    ("reason",))   # handoff (prefill->decode) | shed (load balancing)
ROUTER_DISPATCH_ROLE = REGISTRY.counter(
    "paddle_tpu_serving_router_prefill_decode_dispatch_total",
    "Dispatches by target replica role (disaggregated fleets count "
    "one prefill and one decode dispatch per handed-off request)",
    ("role",))   # prefill|decode|mixed
ROUTER_AFFINITY_HITS = REGISTRY.counter(
    "paddle_tpu_serving_router_affinity_hits_total",
    "Dispatches routed to a replica whose shadow radix index already "
    "held at least one full block of the prompt")
ROUTER_ADAPTER_AFFINITY_HITS = REGISTRY.counter(
    "paddle_tpu_serving_router_adapter_affinity_hits_total",
    "Dispatches steered to a replica whose AdapterCache already held "
    "the request's LoRA adapter resident")
ROUTER_FAILOVERS = REGISTRY.counter(
    "paddle_tpu_serving_router_failovers_total",
    "In-flight requests re-submitted to another replica after their "
    "replica died")
ROUTER_REPLICA_QUEUE_DEPTH = REGISTRY.gauge(
    "paddle_tpu_serving_router_replica_queue_depth",
    "Per-replica load the router balances on: frontend admission "
    "queue + engine FIFO + resident slots", ("replica",))
ROUTER_REPLICAS_UP = REGISTRY.gauge(
    "paddle_tpu_serving_router_replicas_up",
    "Replicas the health layer currently considers dispatchable")

# ---- speculative decoding (draft_k > 0) --------------------------------
SERVING_ACCEPT_LENGTH = REGISTRY.histogram(
    "paddle_tpu_serving_accept_length",
    "Tokens emitted per verify group (accepted draft prefix + the "
    "model's own next token: 1 .. draft_k+1)",
    buckets=(1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
SERVING_DRAFT_TOKENS = REGISTRY.counter(
    "paddle_tpu_serving_draft_tokens_total",
    "Draft tokens by verify outcome", ("outcome",))  # proposed|accepted
SERVING_SPEC_ROLLBACKS = REGISTRY.counter(
    "paddle_tpu_serving_spec_rollbacks_total",
    "Verify groups whose rejected draft tokens forced a KV rollback")
SERVING_SPEC_ROLLBACK_BLOCKS = REGISTRY.counter(
    "paddle_tpu_serving_spec_rollback_blocks_total",
    "KV blocks returned to the free list by draft rollbacks")

# ---- fleet-wide request tracing (serving.tracing, ISSUE 16) ------------
SERVING_TRACES = REGISTRY.counter(
    "paddle_tpu_serving_trace_requests_total",
    "Stitched request traces closed, by terminal outcome",
    ("outcome",))   # finished|expired|cancelled|error
SERVING_TRACE_EVENTS = REGISTRY.counter(
    "paddle_tpu_serving_trace_events_total",
    "Span events recorded into request traces, by event name",
    ("event",))
SERVING_TRACE_EVENTS_DROPPED = REGISTRY.counter(
    "paddle_tpu_serving_trace_events_dropped_total",
    "Span events dropped by the per-trace bound "
    "(PADDLE_TPU_TRACE_EVENTS_MAX) or by trace-table eviction")
SERVING_TRACE_ACTIVE = REGISTRY.gauge(
    "paddle_tpu_serving_trace_active",
    "Open (not yet terminal) request traces — nonzero after a drain "
    "means orphaned spans")
SERVING_TRACE_QUEUE_WAIT = REGISTRY.histogram(
    "paddle_tpu_serving_trace_queue_wait_seconds",
    "Submit-to-first-admission wait derived at the admission span "
    "(fresh prefill admissions only: imports and re-prefills after "
    "preemption do not re-observe)",
    buckets=_LATENCY_BUCKETS)

# ---- SLO plane (serving.slo, ISSUE 16) ---------------------------------
SERVING_SLO_TTFT_P95 = REGISTRY.gauge(
    "paddle_tpu_serving_slo_ttft_p95_seconds",
    "Sliding-window p95 of submit-to-first-token latency", ("tenant",))
SERVING_SLO_INTER_TOKEN_P99 = REGISTRY.gauge(
    "paddle_tpu_serving_slo_inter_token_p99_seconds",
    "Sliding-window p99 of the inter-token gap", ("tenant",))
SERVING_SLO_DEADLINE_MISS_RATIO = REGISTRY.gauge(
    "paddle_tpu_serving_slo_deadline_miss_ratio",
    "Fraction of requests in the window that expired or finished past "
    "their deadline", ("tenant",))
SERVING_SLO_BURN_RATE = REGISTRY.gauge(
    "paddle_tpu_serving_slo_burn_rate",
    "measured / target per objective (>1 = the objective is burning)",
    ("tenant", "objective"))
SERVING_SLO_BREACHES = REGISTRY.counter(
    "paddle_tpu_serving_slo_breaches_total",
    "Edge-triggered objective breaches (ok -> burning transitions "
    "observed by SLOMonitor.evaluate)",
    ("tenant", "objective"))

# ---- fleet control plane (serving.fleet, ISSUE 17) ---------------------
FLEET_REPLICAS = REGISTRY.gauge(
    "paddle_tpu_serving_fleet_replicas",
    "Replicas the fleet controller currently operates, by role and "
    "checkpoint version (a rolling upgrade is the old version's count "
    "draining to zero while the new one's rises)",
    ("role", "version"))
FLEET_BOOTS = REGISTRY.counter(
    "paddle_tpu_serving_fleet_boots_total",
    "Replica boots by kind: cold (fresh engine, empty caches) vs "
    "warm (AOT bundle + restored prefix spill)",
    ("kind",))   # cold|warm
FLEET_UPGRADES = REGISTRY.counter(
    "paddle_tpu_serving_fleet_upgrades_total",
    "Per-replica weight-version flips completed by rolling upgrades "
    "(one drained jitted serving_weight_swap load each)")
FLEET_SCALE_EVENTS = REGISTRY.counter(
    "paddle_tpu_serving_fleet_scale_events_total",
    "Autoscaler decisions applied, by direction and the objective "
    "(or recovery) that drove them",
    ("direction", "reason"))   # up|down x objective|recovered
FLEET_COLD_START = REGISTRY.histogram(
    "paddle_tpu_serving_fleet_cold_start_seconds",
    "Boot-to-ready latency of controller-booted replicas (through "
    "first probe token when the boot carries a probe prompt): the "
    "AOT-vs-jit A/B bench.py's serving_fleet_ops lane measures",
    buckets=exponential_buckets(1e-3, 4.0, 10))

# ---- device-resident multi-tick decode (ISSUE 18) ----------------------
SERVING_TICKS_PER_DISPATCH = REGISTRY.histogram(
    "paddle_tpu_serving_ticks_per_dispatch",
    "Decode ticks the device ran per host dispatch (the lax.while_loop "
    "trip count: ticks_per_dispatch unless an early-exit event — "
    "finish/overflow — returned control to the scheduler sooner)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0))
SERVING_HOST_STALL_SECONDS = REGISTRY.counter(
    "paddle_tpu_serving_host_stall_seconds_total",
    "Wall seconds the host loop spent blocked on device readback of a "
    "tick batch (staging buffer + event bitmask): the dispatch-wall "
    "share the async device_get path is meant to hide")
SERVING_EARLY_EXITS = REGISTRY.counter(
    "paddle_tpu_serving_early_exits_total",
    "Per-slot events that returned control to the scheduler before the "
    "dispatch's tick budget ran out",
    ("reason",))   # finish (EOS/horizon) | overflow (blocks) | reject (draft)

# ---- on-device speculation (ISSUE 19) ----------------------------------
SERVING_SPECULATION_STATE = REGISTRY.gauge(
    "paddle_tpu_serving_speculation_state",
    "Why this replica is or isn't speculating: 1 on exactly one mode — "
    "off (draft_k=0), host (1-tick host n-gram drafting), device "
    "(drafting + verify + sampling history resident in the multi-tick "
    "while_loop; composes with TP and penalized sampling)",
    ("mode",))   # off|host|device

#: every name above, for the smoke-tool contract check
CONTRACT_METRICS = (
    "paddle_tpu_serving_ttft_seconds",
    "paddle_tpu_serving_inter_token_seconds",
    "paddle_tpu_serving_queue_depth",
    "paddle_tpu_serving_active_slots",
    "paddle_tpu_serving_kv_blocks_in_use",
    "paddle_tpu_serving_kv_block_utilization",
    "paddle_tpu_serving_kv_bytes_per_token",
    "paddle_tpu_serving_preemptions_total",
    "paddle_tpu_serving_requests_total",
    "paddle_tpu_serving_tokens_total",
    "paddle_tpu_serving_steps_total",
    "paddle_tpu_serving_accept_length",
    "paddle_tpu_serving_draft_tokens_total",
    "paddle_tpu_serving_spec_rollbacks_total",
    "paddle_tpu_serving_spec_rollback_blocks_total",
    "paddle_tpu_serving_prefix_cache_hit_tokens_total",
    "paddle_tpu_serving_prefix_cache_miss_tokens_total",
    "paddle_tpu_serving_prefix_cache_evictions_total",
    # block-sparse paged decode attention (ISSUE 15): blocks the
    # summary scorer skipped + the cumulative attended fraction
    "paddle_tpu_serving_kv_blocks_skipped_total",
    "paddle_tpu_serving_sparse_attention_ratio",
    "paddle_tpu_serving_router_requests_total",
    "paddle_tpu_serving_router_affinity_hits_total",
    "paddle_tpu_serving_router_failovers_total",
    "paddle_tpu_serving_router_replica_queue_depth",
    "paddle_tpu_serving_router_replicas_up",
    # disaggregated prefill/decode serving (ISSUE 13): block transport
    # volume, migration counts by reason, per-role dispatch, and the
    # stream gap a handoff/shed costs the caller
    "paddle_tpu_serving_kv_blocks_migrated_total",
    "paddle_tpu_serving_kv_transport_bytes_total",
    "paddle_tpu_serving_handoff_latency_seconds",
    "paddle_tpu_serving_router_migrations_total",
    "paddle_tpu_serving_router_prefill_decode_dispatch_total",
    # multi-LoRA adapters (ISSUE 14): slot-cache traffic, eviction
    # churn, load cost, residency, and the router's adapter-affinity
    # steering
    "paddle_tpu_serving_adapter_cache_hits_total",
    "paddle_tpu_serving_adapter_cache_misses_total",
    "paddle_tpu_serving_adapter_evictions_total",
    "paddle_tpu_serving_adapter_load_seconds_total",
    "paddle_tpu_serving_adapters_resident",
    "paddle_tpu_serving_router_adapter_affinity_hits_total",
    # MoE serving (ISSUE 10): per-expert routing volume, capacity
    # drops, cumulative utilization entropy, latest balance loss
    "paddle_tpu_moe_expert_tokens_total",
    "paddle_tpu_moe_dropped_tokens_total",
    "paddle_tpu_moe_expert_utilization",
    "paddle_tpu_moe_aux_loss",
    # fleet-wide request tracing + SLO plane (ISSUE 16): stitched-trace
    # outcomes/volume, orphan gauge, span-derived queue wait, and the
    # per-tenant sliding-window objective gauges the future autoscaler
    # consumes
    "paddle_tpu_serving_trace_requests_total",
    "paddle_tpu_serving_trace_events_total",
    "paddle_tpu_serving_trace_events_dropped_total",
    "paddle_tpu_serving_trace_active",
    "paddle_tpu_serving_trace_queue_wait_seconds",
    "paddle_tpu_serving_slo_ttft_p95_seconds",
    "paddle_tpu_serving_slo_inter_token_p99_seconds",
    "paddle_tpu_serving_slo_deadline_miss_ratio",
    "paddle_tpu_serving_slo_burn_rate",
    "paddle_tpu_serving_slo_breaches_total",
    # trace-discipline guards (ISSUE 12): compile-budget violations +
    # transfer-guard trips observed by analysis.guards.sanitize — the
    # serving one-compile contract's runtime tripwire
    "paddle_tpu_compile_watchdog_budget_exceeded_total",
    "paddle_tpu_compile_watchdog_transfer_guard_trips_total",
    # fleet control plane (ISSUE 17): replica census by role/version,
    # boot kinds, upgrade flips, autoscaler decisions, and the
    # cold-start lane the AOT-boot A/B is judged on
    "paddle_tpu_serving_fleet_replicas",
    "paddle_tpu_serving_fleet_boots_total",
    "paddle_tpu_serving_fleet_upgrades_total",
    "paddle_tpu_serving_fleet_scale_events_total",
    "paddle_tpu_serving_fleet_cold_start_seconds",
    # device-resident multi-tick decode (ISSUE 18): while_loop trip
    # counts per dispatch, the readback stall the async host runtime
    # hides, and the per-slot events that hand control back early
    "paddle_tpu_serving_ticks_per_dispatch",
    "paddle_tpu_serving_host_stall_seconds_total",
    "paddle_tpu_serving_early_exits_total",
    # on-device speculation (ISSUE 19): which speculation mode each
    # replica runs — the operator-facing answer to "why is this
    # replica (not) speculating"
    "paddle_tpu_serving_speculation_state",
)

#: draft-hit ratio = accepted / proposed from SERVING_DRAFT_TOKENS —
#: exported as a plain function so dashboards and the smoke tool agree
#: on the definition
def draft_hit_ratio():
    ch = dict(SERVING_DRAFT_TOKENS.samples())
    prop = ch.get(("proposed",))
    acc = ch.get(("accepted",))
    p = prop.value if prop else 0.0
    return (acc.value if acc else 0.0) / p if p else 0.0
