"""Tuned block-sparse decode budgets (ISSUE 17 satellite).

`ServingEngine(sparse_blocks=B)` trades decode-attention reads for a
fixed per-step block budget; docs/SERVING.md hand-picks B=8 for the
smoke geometry. `tune_sparse_budget` replaces the hand-pick with a
measured sweep on the retrieval ("needle") workload — the adversarial
case for block scoring, where dropping one matching block visibly
corrupts greedy outputs (tools/longctx_smoke.py's contract 2):

* build a dense reference engine and the tuned candidates over the
  SAME long-prompt batch;
* walk `candidates` ascending and keep the SMALLEST budget whose
  greedy token agreement with the dense engine meets
  `agreement_target` (default the 0.99 smoke floor);
* record the winner in the kernel-autotune cache under kernel
  ``sparse_budget``, keyed by `shape_bucket(hidden, head_dim)` — the
  key `ServingEngine(sparse_blocks="auto")` resolves at construction,
  so every later engine of that geometry boots with the tuned budget
  for free (same discipline as the ISSUE 11 `block_size="auto"`).

The sweep runs offline (bench lane / ops runbook), never on a serving
path: one dense + len(candidates) engines, one mixed-step compile
each.
"""
from __future__ import annotations

import numpy as np

__all__ = ["needle_model", "needle_prompts", "tune_sparse_budget"]


def needle_model(num_layers=2, vocab=64, hidden=32, maxpos=256,
                 qk_gain=3.0, pe_scale=0.02):
    """Tiny GPT conditioned into a retrieval transformer: channel-
    sparse embeddings + identity q/k with gain, so attention
    concentrates on same-token ("needle") positions while values /
    projections / lm head keep their random init. The workload
    tools/longctx_smoke.py validates the sparse contract on."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..models.gpt import GPTForGeneration

    paddle.seed(0)
    model = GPTForGeneration(vocab_size=vocab, hidden_size=hidden,
                             num_layers=num_layers,
                             num_attention_heads=1,
                             max_position_embeddings=maxpos,
                             compute_dtype="float32")
    we = np.zeros((vocab, hidden), np.float32)
    we[np.arange(vocab), np.arange(vocab) % hidden] = 1.0
    model.word_embeddings.weight._data = jnp.asarray(we)
    model.position_embeddings.weight._data = (
        jnp.asarray(model.position_embeddings.weight._data) * pe_scale)
    names, dec = model.decoder._param_tensors()
    eye = jnp.eye(hidden, dtype=jnp.float32)
    for n, t in zip(names, dec):
        if n == "qkv_w":
            w = jnp.asarray(t._data)
            L = w.shape[0]
            w = w.at[:, :, :hidden].set(qk_gain * eye[None].repeat(L, 0))
            w = w.at[:, :, hidden:2 * hidden].set(
                qk_gain * eye[None].repeat(L, 0))
            t._data = w
    model.eval()
    return model


def needle_prompts(n=16, lo=90, hi=200, vocab=64, seed=7):
    """Long random prompts (tens of candidate blocks per slot by the
    end of decode) — the regime where a too-small budget must drop
    scored blocks and lose needles."""
    rng = np.random.RandomState(seed)
    return [rng.randint(2, vocab, int(k)).tolist()
            for k in rng.randint(lo, hi, n)]


def tune_sparse_budget(model=None, *, candidates=(4, 6, 8, 12, 16),
                       sparse_recent=2, agreement_target=0.99,
                       prompts=None, max_new_tokens=12,
                       max_seq_len=224, block_size=4, max_slots=4,
                       persist=True, verbose=False):
    """Sweep `candidates` (ascending block budgets B) on the needle
    workload; record the smallest B meeting `agreement_target` in the
    autotune cache and return

        {"best": {"sparse_blocks": B, "sparse_recent": r} | None,
         "agreement": float, "skip_ratio": float, "bucket": (...),
         "sweep": [{"sparse_blocks", "agreement", "skip_ratio"}, ...]}

    `best` is None (and nothing is recorded) when no candidate meets
    the floor — `sparse_blocks="auto"` then keeps its conservative
    default."""
    from ..ops.pallas import autotune as _kt
    from .engine import ServingEngine

    if model is None:
        model = needle_model()
    if prompts is None:
        prompts = needle_prompts(vocab=int(model.vocab_size))

    def engine(**kw):
        return ServingEngine(model, max_slots=max_slots,
                             block_size=block_size,
                             max_seq_len=max_seq_len,
                             cache_dtype="float32", seed=0, **kw)

    dense = engine()
    ref = dense.generate_batch([list(p) for p in prompts],
                               max_new_tokens=max_new_tokens)
    total = sum(len(o) for o in ref)
    H = int(model.hidden_size)
    Dh = H // int(model.decoder.num_heads)
    bucket = _kt.shape_bucket(H, Dh)
    sweep, best = [], None
    for B in sorted(int(b) for b in candidates):
        eng = engine(sparse_blocks=B, sparse_recent=int(sparse_recent))
        out = eng.generate_batch([list(p) for p in prompts],
                                 max_new_tokens=max_new_tokens)
        agree = sum(a == b for x, y in zip(ref, out)
                    for a, b in zip(x, y)) / max(1, total)
        row = {"sparse_blocks": B, "agreement": agree,
               "skip_ratio": eng.sparse_skip_ratio()}
        sweep.append(row)
        if verbose:
            print(f"  B={B:3d} agreement={agree:.4f} "
                  f"skip={row['skip_ratio']:.3f}")
        if best is None and agree >= agreement_target:
            best = row
            # candidates are ascending, so the first hit IS the
            # smallest budget; keep sweeping only for the report
    result = {"best": None, "agreement": 0.0, "skip_ratio": 0.0,
              "bucket": bucket, "sweep": sweep}
    if best is not None:
        cfg = {"sparse_blocks": best["sparse_blocks"],
               "sparse_recent": int(sparse_recent)}
        _kt.record("sparse_budget", bucket, np.dtype(np.float32), cfg,
                   meta={"agreement": best["agreement"],
                         "skip_ratio": best["skip_ratio"],
                         "target": float(agreement_target)},
                   persist=persist)
        result.update(best=cfg, agreement=best["agreement"],
                      skip_ratio=best["skip_ratio"])
    return result
