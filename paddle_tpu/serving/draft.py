"""Self-drafting proposers for speculative decoding.

The cheapest draft model is the sequence itself: natural (and
especially code/log-like) text repeats, so the continuation of the
most recent earlier occurrence of the current tail n-gram is a strong
guess for the next few tokens — "prompt lookup" decoding. No second
model, no device work: the proposer runs host-side over the request's
token list between engine steps.

Correctness never depends on draft quality: the verify step
(`incubate/nn/generation.py` speculative path, `serving/engine.py`
mixed step) scores every proposed token against the real model and
emits only the sequential-greedy prefix, so a bad draft costs speed,
not output fidelity.

Two implementations of the same proposer live here (ISSUE 19):

* `ngram_propose` — the host reference, a plain python scan over the
  request's token list. The 1-tick engine drafts with it between
  steps.
* `ngram_propose_device` — the `jnp` twin the multi-tick engine
  traces INTO the mixed step's while_loop body: a fixed
  `[max_slots, k]` proposal batch computed from the per-slot token
  ring buffer (`ring_chronological`), so drafting advances on device
  without a host round-trip. Given the same trailing window the two
  produce IDENTICAL proposals (tests/test_speculative.py asserts
  this), which is what keeps an N-tick speculative engine
  token-identical to the N=1 host-drafting reference.
"""
from __future__ import annotations


def accept_length(fed_tokens, scored_tokens):
    """Longest accepted draft prefix for one verify group.

    `fed_tokens` = [last_accepted, d_1..d_k] as fed to the verify step;
    `scored_tokens[j]` = the model's greedy next token after fed token
    j. Returns m: d_1..d_m matched the model exactly, so the emitter
    takes `scored_tokens[:m + 1]` (the accepted drafts re-derived from
    the model's own outputs, plus its correction after the last match).
    This off-by-one contract lives HERE, once — the generate() loop and
    the serving engine must never disagree on it."""
    m = 0
    while m < len(fed_tokens) - 1 and \
            int(fed_tokens[m + 1]) == int(scored_tokens[m]):
        m += 1
    return m


def accept_length_sampled(fed_tokens, accept_flags):
    """Longest accepted draft prefix under REJECTION sampling.

    `accept_flags[j]` is the device's verdict on draft `d_{j+1}`
    (uniform u_j < p_j(d_{j+1}) against the target distribution at
    verify position j — serving/engine.py). Returns m: drafts
    d_1..d_m were accepted; the emitter then takes the device's
    residual resample at position m (rejection there) or its bonus
    sample (all drafts accepted, m == len(fed_tokens) - 1). Same
    off-by-one contract as `accept_length`, same single home."""
    m = 0
    while m < len(fed_tokens) - 1 and bool(accept_flags[m]):
        m += 1
    return m


def ngram_propose(tokens, k, max_ngram=3, min_ngram=1):
    """Propose `k` draft tokens for the sequence `tokens`.

    Finds the longest trailing n-gram (n from `max_ngram` down to
    `min_ngram`) with an earlier occurrence in the sequence — the MOST
    RECENT occurrence wins, matching the local context — and copies the
    k tokens that followed it. Short continuations (or no match at all)
    are padded by repeating the last available token, so the caller
    always gets exactly `k` proposals (the verify step's shape never
    depends on draft luck)."""
    k = int(k)
    if k <= 0:
        return []
    toks = [int(t) for t in tokens]
    n_t = len(toks)
    out = []
    for n in range(min(int(max_ngram), n_t - 1), int(min_ngram) - 1, -1):
        tail = toks[n_t - n:]
        for s in range(n_t - n - 1, -1, -1):
            if toks[s:s + n] == tail:
                out = toks[s + n:s + n + k]
                break
        if out:
            break
    pad = out[-1] if out else (toks[-1] if toks else 0)
    while len(out) < k:
        out.append(pad)
    return out


def ring_chronological(ring, count):
    """Circular per-slot token ring -> right-aligned chronological view.

    `ring` [S, W] int32 holds each slot's last (up to) W tokens with
    token t of the sequence stored at column t % W; `count` [S] is the
    TOTAL sequence length so far. Returns `view` [S, W] where
    view[:, -1] is each slot's most recent token and only the last
    min(count, W) columns are meaningful — the layout
    `ngram_propose_device` scans. One gather, fixed shape."""
    import jax.numpy as jnp
    W = ring.shape[1]
    idx = (count[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]) % W
    return jnp.take_along_axis(ring, idx, axis=1)


def ngram_propose_device(view, length, k, max_ngram=3, min_ngram=1):
    """`jnp` twin of `ngram_propose`, batched over slots.

    `view` [S, W] is the chronological window (`ring_chronological`),
    `length` [S] the true sequence length (columns before W -
    min(length, W) are garbage and never matched). Returns [S, k]
    int32 proposals, identical to running the host proposer on each
    slot's trailing W-token window.

    The scan is O(W * max_ngram) fixed-shape work: ml[j] = the length
    of the suffix match between the window ending at column j and the
    window's own tail (capped at max_ngram, never crossing the valid
    region). The host picks the LONGEST tail n-gram first and the MOST
    RECENT occurrence within it, which is exactly the lexicographic
    argmax of (ml[j], j) — encoded as one argmax over ml[j] * W + j.
    The continuation (clamped at the window end) repeats the last
    available token, reproducing the host's truncate-then-pad."""
    import jax.numpy as jnp
    k = int(k)
    S, W = view.shape
    j = jnp.arange(W, dtype=jnp.int32)[None, :]           # [1, W]
    L = jnp.minimum(length, W).astype(jnp.int32)[:, None]  # [S, 1]
    run = jnp.ones((S, W), bool)
    ml = jnp.zeros((S, W), jnp.int32)
    for i in range(int(max_ngram)):
        # compare column j - i against the tail token at W - 1 - i;
        # out-of-window positions (j - i < W - L) can never match, so
        # ml is automatically capped at min(max_ngram, L - 1) for any
        # candidate end column — the host's n <= n_t - 1 bound
        shifted = jnp.pad(view, ((0, 0), (i, 0)))[:, :W]
        run = run & (j - i >= W - L) & (shifted == view[:, W - 1 - i,
                                                        None])
        ml = ml + run.astype(jnp.int32)
    # a candidate end column must close a match of at least min_ngram
    # and sit strictly before the last column (the host's earlier-
    # occurrence constraint); scores are unique per (ml, j) pair
    cand = (ml >= int(min_ngram)) & (j <= W - 2)
    score = jnp.where(cand, ml * W + j, -1)
    best = jnp.argmax(score, axis=1).astype(jnp.int32)     # [S]
    has = jnp.max(score, axis=1) >= 0
    end = jnp.where(has, best, W - 1)
    cont = jnp.minimum(end[:, None] + 1
                       + jnp.arange(k, dtype=jnp.int32)[None, :],
                       W - 1)
    return jnp.take_along_axis(view, cont, axis=1).astype(jnp.int32)
