"""Self-drafting proposers for speculative decoding.

The cheapest draft model is the sequence itself: natural (and
especially code/log-like) text repeats, so the continuation of the
most recent earlier occurrence of the current tail n-gram is a strong
guess for the next few tokens — "prompt lookup" decoding. No second
model, no device work: the proposer runs host-side over the request's
token list between engine steps.

Correctness never depends on draft quality: the verify step
(`incubate/nn/generation.py` speculative path, `serving/engine.py`
mixed step) scores every proposed token against the real model and
emits only the sequential-greedy prefix, so a bad draft costs speed,
not output fidelity.
"""
from __future__ import annotations


def accept_length(fed_tokens, scored_tokens):
    """Longest accepted draft prefix for one verify group.

    `fed_tokens` = [last_accepted, d_1..d_k] as fed to the verify step;
    `scored_tokens[j]` = the model's greedy next token after fed token
    j. Returns m: d_1..d_m matched the model exactly, so the emitter
    takes `scored_tokens[:m + 1]` (the accepted drafts re-derived from
    the model's own outputs, plus its correction after the last match).
    This off-by-one contract lives HERE, once — the generate() loop and
    the serving engine must never disagree on it."""
    m = 0
    while m < len(fed_tokens) - 1 and \
            int(fed_tokens[m + 1]) == int(scored_tokens[m]):
        m += 1
    return m


def accept_length_sampled(fed_tokens, accept_flags):
    """Longest accepted draft prefix under REJECTION sampling.

    `accept_flags[j]` is the device's verdict on draft `d_{j+1}`
    (uniform u_j < p_j(d_{j+1}) against the target distribution at
    verify position j — serving/engine.py). Returns m: drafts
    d_1..d_m were accepted; the emitter then takes the device's
    residual resample at position m (rejection there) or its bonus
    sample (all drafts accepted, m == len(fed_tokens) - 1). Same
    off-by-one contract as `accept_length`, same single home."""
    m = 0
    while m < len(fed_tokens) - 1 and bool(accept_flags[m]):
        m += 1
    return m


def ngram_propose(tokens, k, max_ngram=3, min_ngram=1):
    """Propose `k` draft tokens for the sequence `tokens`.

    Finds the longest trailing n-gram (n from `max_ngram` down to
    `min_ngram`) with an earlier occurrence in the sequence — the MOST
    RECENT occurrence wins, matching the local context — and copies the
    k tokens that followed it. Short continuations (or no match at all)
    are padded by repeating the last available token, so the caller
    always gets exactly `k` proposals (the verify step's shape never
    depends on draft luck)."""
    k = int(k)
    if k <= 0:
        return []
    toks = [int(t) for t in tokens]
    n_t = len(toks)
    out = []
    for n in range(min(int(max_ngram), n_t - 1), int(min_ngram) - 1, -1):
        tail = toks[n_t - n:]
        for s in range(n_t - n - 1, -1, -1):
            if toks[s:s + n] == tail:
                out = toks[s + n:s + n + k]
                break
        if out:
            break
    pad = out[-1] if out else (toks[-1] if toks else 0)
    while len(out) < k:
        out.append(pad)
    return out
