"""Continuous-batching scheduler.

FIFO admission over a fixed set of slots, chunked prefill under a
per-step token budget, and block-pressure preemption against the paged
KV cache:

* **Admission** — requests queue FIFO; a request is admitted to the
  lowest free slot as soon as one exists. Prefill then streams the
  prompt through the mixed step in budget-sized chunks (so one giant
  prompt cannot starve running decodes: decodes are planned FIRST each
  step, prefill fills the remaining budget).
* **Preemption** — when a decode cannot get its next KV block, the
  scheduler evicts the decode holding the MOST blocks (the
  longest-running sequence — freeing the most memory per eviction;
  ties break toward the latest arrival, preserving FIFO fairness).
  The victim re-enters the FRONT of the queue with its generated
  prefix folded into the prompt, so a later re-prefill resumes the
  sequence exactly. Prefill never preempts (only free blocks), which
  keeps admission from thrashing running decodes.
* **Deadlines** — an optional absolute deadline per request; queued or
  resident requests past it are expired and their blocks reclaimed.
* **Migration** (disaggregated serving, docs/SERVING.md) — a request
  arriving from another replica (`submit_migrated`) joins the FRONT of
  the queue carrying its KV payload; admission IMPORTS the blocks into
  a slot (`kv.import_into_slot`) instead of prefilling, and `extract`
  releases a resident request migrating away (its blocks were exported
  by the engine first). Prefill-role engines park completed prompts in
  the `"handoff"` state, which plans neither prefill nor decode.

The scheduler is pure host-side bookkeeping — it orchestrates through
the kv-cache API (which owns any device work, like the import scatter)
and never touches device arrays itself; the engine turns its plans
into the fixed-shape step inputs.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Optional

from . import batcher
from . import tracing as _tracing


@dataclasses.dataclass(eq=False)   # identity semantics: requests live
class Request:                     # in sets/queues across state moves
    req_id: int
    prompt: list                      # original prompt token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    deadline: Optional[float] = None  # absolute time.monotonic()
    arrival: float = 0.0
    state: str = "queued"
    # queued|prefill|handoff|decode|finished|expired|cancelled|migrated
    slot: int = -1
    output: list = dataclasses.field(default_factory=list)
    fed: int = 0                      # runtime-prompt tokens fed so far
    preemptions: int = 0
    cache_hit_tokens: int = 0         # prefix-cache tokens skipped
    tenant: str = "default"           # frontend fairness bucket
    # multi-LoRA (serving.adapters): the registered adapter this
    # request decodes under (None = base model) and, while resident,
    # the device slot its pin holds (0 = the reserved null slot)
    adapter_id: object = None
    adapter_slot: int = 0
    # disaggregated serving (serving.distributed.transport): inbound
    # migrations carry their KV payload until admission imports it;
    # prefill-role engines track which full blocks were already
    # streamed ahead so extraction ships only the tail
    ticket: Optional[object] = None
    shipped_blocks: int = 0
    # fleet-wide request tracing (serving.tracing, ISSUE 16): minted at
    # router dispatch and carried across migrations via the ticket so
    # one stitched trace covers every replica the request touched
    trace_id: Optional[str] = None
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    _last_token_time: Optional[float] = None

    @property
    def runtime_prompt(self):
        """What prefill must feed: the prompt plus any tokens already
        generated before a preemption dropped the KV blocks."""
        return self.prompt + self.output

    @property
    def done(self):
        return self.state in ("finished", "expired", "cancelled")


@dataclasses.dataclass
class Plan:
    decode: list        # [(slot, token, position)]
    prefills: list      # [(slot, chunk ndarray, start_pos, completes)]
    expired: list       # requests expired this round

    @property
    def empty(self):
        return not self.decode and not self.prefills


class Scheduler:
    def __init__(self, kv_cache, *, max_slots, token_budget,
                 clock=time.monotonic, draft_k=0, draft_fn=None,
                 device_draft=False, prefix_cache=None,
                 adapter_cache=None, reserve_region=False):
        self.kv = kv_cache
        self.max_slots = max_slots
        self.token_budget = token_budget
        self.clock = clock
        self.queue = collections.deque()
        self.slots = [None] * max_slots
        self._ids = itertools.count()
        self.preemption_count = 0
        # speculative decoding: each decode may carry up to draft_k
        # proposed tokens (draft_fn(seq) -> list of draft_k ints); the
        # engine verifies them and advances slot_lens itself, so
        # note_fed leaves decode lengths alone when draft_k > 0
        self.draft_k = int(draft_k)
        self.draft_fn = draft_fn
        # device-resident drafting (ISSUE 19): the multi-tick engine
        # proposes drafts INSIDE the while_loop from the on-device
        # token ring, so plan() emits plain single-token decode groups
        # ([last] only — the device widens them) while the reserved-
        # region budget and note_fed/note_accept bookkeeping keep the
        # full draft_k treatment
        self.device_draft = bool(device_draft)
        # radix prefix cache (serving.prefix_cache): admission skips
        # cached prompt heads, prefill completion / finish publish the
        # written blocks for later requests
        self.prefix_cache = prefix_cache
        # multi-LoRA adapter cache (serving.adapters): admission pins
        # the request's adapter into a device slot — and BLOCKS at the
        # queue head when every slot is pinned by in-flight requests;
        # `_free_slot` drops the pin on every release path
        self.adapters = adapter_cache
        # block-sparse decode (ISSUE 15): the engine reserves the
        # per-slot decode region even at draft_k == 0, so prefill
        # budgets must treat it as spoken for exactly like the
        # speculative verify region
        self.reserve_region = bool(reserve_region)
        # replica label the tracing hooks stamp on span events; the
        # owning engine overwrites it with its own name
        self.replica = None

    # ---------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               deadline=None, tenant="default", adapter_id=None,
               trace_id=None):
        total = len(prompt) + max_new_tokens - 1  # last token never fed
        if total > self.kv.max_slot_tokens:
            raise ValueError(
                f"request needs {total} cached tokens; a slot holds at "
                f"most {self.kv.max_slot_tokens}")
        if adapter_id is not None and self.adapters is None:
            raise ValueError("request names an adapter but the "
                             "scheduler has no adapter cache")
        now = self.clock()
        req = Request(req_id=next(self._ids), prompt=list(prompt),
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id, deadline=deadline,
                      arrival=now, submit_time=now, tenant=str(tenant),
                      adapter_id=adapter_id, trace_id=trace_id)
        self.queue.append(req)
        if _tracing._enabled:
            _tracing.on_submit(req, self.replica)
        return req

    def submit_migrated(self, ticket):
        """Queue a request migrated in from another replica: its KV
        payload rides `req.ticket` until a slot frees and the blocks
        fit, then admission IMPORTS the blocks instead of prefilling.
        Joins the FRONT of the queue — like a preemption victim, the
        request is already mid-stream and its caller is watching the
        token gap. Timing fields carry over so TTFT is observed once
        (on the source) and inter-token histograms stay continuous."""
        total = len(ticket.prompt) + int(ticket.max_new_tokens) - 1
        if total > self.kv.max_slot_tokens:
            raise ValueError(
                f"migrated request needs {total} cached tokens; a slot "
                f"holds at most {self.kv.max_slot_tokens}")
        now = self.clock()
        req = Request(req_id=next(self._ids),
                      prompt=list(ticket.prompt),
                      max_new_tokens=int(ticket.max_new_tokens),
                      eos_token_id=ticket.eos_token_id,
                      deadline=ticket.deadline,
                      arrival=now, submit_time=ticket.submit_time,
                      tenant=str(ticket.tenant),
                      output=list(ticket.output),
                      cache_hit_tokens=int(ticket.cache_hit_tokens),
                      preemptions=int(ticket.preemptions),
                      ticket=ticket,
                      adapter_id=getattr(ticket, "adapter_id", None),
                      trace_id=getattr(ticket, "trace_id", None))
        req.first_token_time = ticket.first_token_time
        self.queue.appendleft(req)
        if _tracing._enabled:
            _tracing.on_submit_migrated(req, self.replica, ts=now)
        return req

    @property
    def num_active(self):
        return sum(s is not None for s in self.slots)

    @property
    def has_work(self):
        return bool(self.queue) or self.num_active > 0

    # ------------------------------------------------------- internals
    def _free_slot(self, req):
        if self.prefix_cache is not None:
            self.prefix_cache.unlock_slot(req.slot)
        if self.adapters is not None and req.adapter_id is not None:
            # every release path (finish/preempt/expire/cancel/extract)
            # funnels through here, so each admission's pin is dropped
            # exactly once; the adapter stays resident until LRU
            # eviction needs its slot
            self.adapters.release(req.adapter_id)
            req.adapter_slot = 0
        self.kv.release_slot(req.slot)
        self.slots[req.slot] = None
        req.slot = -1

    def _expire(self, now):
        expired = []
        for req in list(self.queue):
            if req.deadline is not None and now > req.deadline:
                self.queue.remove(req)
                req.state = "expired"
                req.finish_time = now
                expired.append(req)
        for req in list(self.slots):
            if req is not None and req.deadline is not None \
                    and now > req.deadline:
                self._free_slot(req)
                req.state = "expired"
                req.finish_time = now
                expired.append(req)
        if _tracing._enabled:
            for req in expired:
                _tracing.on_terminal(req, "expired", self.replica,
                                     ts=now)
        return expired

    def _acquire_adapter(self, req):
        """Pin the queue head's adapter into a device slot. True on
        success (or no adapter); False = every slot is pinned by
        in-flight requests — admission BLOCKS at the head until one
        finishes (residency gating, never slot corruption)."""
        if self.adapters is None or req.adapter_id is None:
            req.adapter_slot = 0
            return True
        slot_a = self.adapters.acquire(req.adapter_id)
        if slot_a is None:
            return False
        req.adapter_slot = int(slot_a)
        return True

    def _admit(self):
        for slot in range(self.max_slots):
            if not self.queue:
                break
            if self.slots[slot] is None:
                if self.queue[0].ticket is not None:
                    # migrated request at the head: admission imports
                    # its transported KV blocks instead of prefilling.
                    # If the free list (after prefix-cache eviction)
                    # can't cover them yet, it WAITS at the head —
                    # head-of-line priority is deliberate: the request
                    # is mid-stream and resuming it beats admitting
                    # fresh prompts behind it.
                    req = self.queue[0]
                    if not self._acquire_adapter(req):
                        break
                    if not self.kv.import_into_slot(
                            slot, req.ticket.slot_len,
                            req.ticket.chunks):
                        # release the fresh pin so the retry next plan
                        # can't stack a second one
                        if self.adapters is not None \
                                and req.adapter_id is not None:
                            self.adapters.release(req.adapter_id)
                            req.adapter_slot = 0
                        break
                    self.queue.popleft()
                    req.slot = slot
                    req.state = "decode"
                    # the whole runtime prompt's K/V is resident — the
                    # next step feeds output[-1] at position slot_len,
                    # exactly like a post-prefill decode
                    req.fed = len(req.runtime_prompt)
                    req.ticket = None          # payload consumed
                    self.slots[slot] = req
                    if _tracing._enabled:
                        _tracing.on_admitted(req, self.replica,
                                             kind="import",
                                             ts=self.clock())
                    continue
                if not self._acquire_adapter(self.queue[0]):
                    break
                req = self.queue.popleft()
                req.slot = slot
                req.state = "prefill"
                req.fed = 0
                self.slots[slot] = req
                if _tracing._enabled:
                    # a re-prefill resumes a preempted sequence (its
                    # generated prefix folds into the prompt) — a
                    # distinct span kind so queue-wait is observed
                    # only on the FIRST admission
                    kind = ("re_prefill"
                            if (req.output or req.preemptions)
                            else "prefill")
                    _tracing.on_admitted(req, self.replica, kind=kind,
                                         ts=self.clock())
                if self.prefix_cache is not None \
                        and req.adapter_id is None:
                    # cached prompt head: adopt the shared blocks, mark
                    # their K/V as already resident, and start chunked
                    # prefill at the first uncached token. Re-admission
                    # after a preemption rides the same path — the
                    # victim's own published blocks usually cover most
                    # of its re-prefill. Requests under a non-null
                    # adapter BYPASS the prefix cache entirely: their
                    # K/V depends on the adapter, and the radix tree
                    # keys by token ids alone — sharing across
                    # adapters would serve another finetune's cache.
                    hit = self.prefix_cache.lookup_and_adopt(
                        slot, req.runtime_prompt)
                    req.fed = hit
                    req.cache_hit_tokens += hit
                    self.kv.slot_lens[slot] = hit
        return

    def _preempt_victim(self, exclude):
        """Evict the decode holding the most blocks (tie: latest
        arrival). Returns the victim or None."""
        cands = [r for r in self.slots
                 if r is not None and r.state == "decode"
                 and r not in exclude]
        if not cands:
            return None
        victim = max(cands, key=lambda r: (self.kv.slot_num_blocks(
            r.slot), r.arrival))
        self._free_slot(victim)
        victim.state = "queued"
        victim.fed = 0
        victim.preemptions += 1
        self.preemption_count += 1
        self.queue.appendleft(victim)
        if _tracing._enabled:
            _tracing.on_preempted(victim, self.replica,
                                  ts=self.clock())
        return victim

    # ------------------------------------------------- speculative draft
    def _draft_tokens(self, req, pos):
        """[last_token, d_1..d_k] for one decode's verify group.

        k starts at draft_k and shrinks to what is actually worth
        feeding: never past the request's remaining horizon (a draft
        beyond max_new_tokens could only emit discarded tokens), never
        past the slot's token capacity, and never past what FREE blocks
        can back — draft tokens extend only with free blocks, exactly
        like prefill chunks, so a speculative burst can't preempt a
        neighbour's accepted work."""
        k = min(self.draft_k,
                req.max_new_tokens - len(req.output) - 1,
                self.kv.max_slot_tokens - (pos + 1))
        if k > 0:
            # free-block extension only: shrink k to the free coverage
            while k > 0 and not self.kv.ensure_capacity(
                    req.slot, pos + 1 + k):
                fit = (self.kv.slot_num_blocks(req.slot)
                       + self.kv.allocator.num_free) \
                    * self.kv.block_size - (pos + 1)
                k = min(k - 1, fit) if fit > 0 else 0
        if k <= 0:
            return [req.output[-1]]
        draft = self.draft_fn(req.prompt + req.output)
        return [req.output[-1]] + [int(t) for t in draft[:k]]

    # ------------------------------------------- multi-tick preallocation
    def extend_for_ticks(self, slot, pos, n_ticks):
        """Pre-extend one decode slot's block tables so a multi-tick
        dispatch (engine `ticks_per_dispatch`, docs/SERVING.md) can
        append up to `n_ticks` tokens starting at `pos` without host
        intervention. The first tick's block is already guaranteed by
        `plan()` (with preemption); the extra ticks extend with FREE
        blocks only — exactly the draft/prefill discipline — so a tick
        burst can never evict a neighbour's resident KV. Returns the
        capacity in tokens the dispatch may fill (`cap`, with
        pos + 1 <= cap <= pos + n_ticks); the engine truncates back to
        what was actually emitted at harvest, so the block accounting
        at every dispatch boundary matches a 1-tick engine's."""
        k = min(int(n_ticks) - 1, self.kv.max_slot_tokens - (pos + 1))
        while k > 0 and not self.kv.ensure_capacity(slot, pos + 1 + k):
            fit = (self.kv.slot_num_blocks(slot)
                   + self.kv.allocator.num_free) \
                * self.kv.block_size - (pos + 1)
            k = min(k - 1, fit) if fit > 0 else 0
        return pos + 1 + max(k, 0)

    # ------------------------------------------------------------ plan
    def plan(self) -> Plan:
        """One engine iteration's work. Mutates scheduler/cache state
        (admissions, block allocation, preemptions, expiries)."""
        now = self.clock()
        expired = self._expire(now)
        self._admit()

        decode = []
        protected = set()
        # decodes first, oldest arrival first: block pressure falls on
        # the youngest/longest sequences, never the queue head
        decoders = sorted(
            (r for r in self.slots
             if r is not None and r.state == "decode"),
            key=lambda r: r.arrival)
        for req in decoders:
            if req.slot < 0:    # preempted by an earlier iteration
                continue
            # position of the token being fed = tokens already cached
            pos = int(self.kv.slot_lens[req.slot])
            while not self.kv.ensure_capacity(req.slot, pos + 1):
                if self._preempt_victim(protected | {req}) is None:
                    # nothing left to evict: preempt THIS decode
                    self._preempt_victim(protected)
                    break
            if req.slot < 0:
                continue
            protected.add(req)
            if self.draft_k > 0 and not self.device_draft:
                decode.append((req.slot,
                               self._draft_tokens(req, pos), pos))
            elif self.draft_k > 0:
                # device drafting: feed only the last accepted token —
                # the engine's extend_for_ticks preallocation covers
                # the verify burst, and the loop body widens the group
                decode.append((req.slot, [req.output[-1]], pos))
            else:
                decode.append((req.slot, req.output[-1], pos))

        # with speculation (or the sparse decode region) the region is
        # RESERVED up front (see batcher.pack_step) — prefill budget
        # never depends on the mix
        reserved = len(decode) \
            if self.draft_k == 0 and not self.reserve_region \
            else self.max_slots * (self.draft_k + 1)
        budget_left = self.token_budget - reserved
        prefills = []
        prefillers = sorted(
            (r for r in self.slots
             if r is not None and r.state == "prefill"),
            key=lambda r: r.arrival)
        for req in prefillers:
            if budget_left <= 0:
                break
            tokens = req.runtime_prompt
            remaining = len(tokens) - req.fed
            chunk = batcher.prefill_chunk(remaining, budget_left)
            # prefill only uses FREE blocks — shrink to what fits
            while chunk > 0 and not self.kv.ensure_capacity(
                    req.slot, req.fed + chunk):
                fit = (self.kv.slot_num_blocks(req.slot)
                       + self.kv.allocator.num_free) \
                    * self.kv.block_size - req.fed
                chunk = min(chunk - 1, fit) if fit > 0 else 0
            if chunk <= 0:
                continue
            import numpy as np
            arr = np.asarray(tokens[req.fed:req.fed + chunk], np.int32)
            completes = req.fed + chunk == len(tokens)
            prefills.append((req.slot, arr, req.fed, completes))
            req.fed += chunk
            budget_left -= chunk
        return Plan(decode=decode, prefills=prefills, expired=expired)

    # ------------------------------------------------- post-step hooks
    def note_fed(self, plan: Plan):
        """Advance slot lengths for every token the step consumed.

        Speculative decodes are NOT advanced here: how far a verify
        group really got is only known after the engine reads the
        accept length back, so `note_accept` owns that bookkeeping."""
        if self.draft_k == 0:
            for slot, _tok, pos in plan.decode:
                self.kv.slot_lens[slot] = pos + 1
        for slot, chunk, start, completes in plan.prefills:
            self.kv.slot_lens[slot] = start + len(chunk)
            if completes and self.prefix_cache is not None:
                # the whole prompt's K/V is resident now — publish its
                # full blocks so concurrent same-prefix requests hit
                # (base-model requests only: adapter K/V must never
                # enter the token-keyed tree)
                req = self.slots[slot]
                if req is not None and req.adapter_id is None:
                    self.prefix_cache.insert(slot, req.runtime_prompt)

    def note_accept(self, slot, new_len):
        """Record a verify group's outcome: `new_len` tokens of the
        slot are cached and valid; blocks allocated for rejected draft
        tokens beyond it are rolled back. Returns blocks freed."""
        self.kv.slot_lens[slot] = new_len
        return self.kv.truncate_slot(slot, new_len)

    def finish(self, req, now=None):
        req.state = "finished"
        req.finish_time = self.clock() if now is None else now
        if self.prefix_cache is not None and req.slot >= 0 \
                and req.adapter_id is None:
            # publish prompt + generated history (chat-turn reuse);
            # only tokens whose K/V was actually written count — the
            # last emitted token never fed the step
            n = int(self.kv.slot_lens[req.slot])
            self.prefix_cache.insert(req.slot,
                                     (req.prompt + req.output)[:n])
        self._free_slot(req)
        if _tracing._enabled:
            _tracing.on_terminal(req, "finished", self.replica,
                                 ts=req.finish_time)

    def extract(self, req, now=None):
        """Release a resident request that is migrating away: its slot,
        blocks and prefix locks are reclaimed here (the engine exported
        the block payload FIRST), and the request reaches the terminal-
        for-this-replica state "migrated" — it keeps producing tokens,
        just on another engine. Shared prefix blocks the slot adopted
        stay cached (refcounted), so the source replica keeps serving
        the prefix to future same-head requests."""
        if req.slot < 0:
            raise ValueError(f"request {req.req_id} is not resident")
        self._free_slot(req)
        req.state = "migrated"
        req.finish_time = self.clock() if now is None else now

    def cancel(self, req, now=None):
        """Abort a queued or resident request: its blocks (and prefix
        locks) are reclaimed and it never produces another token.
        Returns False when the request already reached a terminal
        state."""
        if req.done:
            return False
        if req.state == "queued":
            try:
                self.queue.remove(req)
            except ValueError:
                return False
        elif req.slot >= 0:
            self._free_slot(req)
        req.state = "cancelled"
        req.finish_time = self.clock() if now is None else now
        if _tracing._enabled:
            _tracing.on_terminal(req, "cancelled", self.replica,
                                 ts=req.finish_time)
        return True
