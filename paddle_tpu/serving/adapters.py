"""Multi-LoRA adapter slots for the one-compile serving mixed step.

Multi-tenant serving means thousands of *variants* of one base model —
per-customer finetunes — and the only way that scales is near-zero
marginal HBM per tenant (ROADMAP item 3; the reference fork's
`weight_only_linear_kernel.h` + `fused_multi_transformer_moe_*` pair
exists for exactly this serving shape). The design mirrors the paged
KV cache's shape discipline:

* **Fixed adapter slot tensors.** Each hooked projection (fused qkv,
  attention out, and — dense stacks — ffn1/ffn2) owns two device
  tensors `A [L, max_adapters, d_in, r]` and `B [L, max_adapters, r,
  d_out]` that ride the compiled mixed step as ordinary inputs: which
  adapters are resident NEVER changes a compiled shape, so adapter
  loads, evictions and churn keep the one-compile contract
  (watchdog-enforced). The leading `L` axis rides the step's
  `lax.scan` over layers exactly like the stacked base weights.
* **Per-token adapter ids** ride the flat token axis the way sampling
  params do: the engine rebuilds a `[T]` int32 vector from the
  scheduler's slot table each step and the step body turns it into
  one `[T, K]` one-hot that every layer's `_lora_delta` reuses
  (`incubate.nn.fused_transformer._lora_delta` — the one-hot select
  keeps the delta K*T*d*r flops with no `[T, d, r]` gather).
* **Slot 0 is the NULL adapter** — all-zero A/B, never assigned,
  never evicted. Base-model requests (and padding tokens) carry
  adapter id 0, their delta is exactly 0.0, and their tokens are
  identical to an engine built with no adapter support at all
  (tools/lora_smoke.py asserts this).
* **The host cache reuses the prefix-cache machinery's shape**:
  refcounted pins (every resident request pins its adapter — a pinned
  slot is never evicted, so admission BLOCKS instead of corrupting a
  neighbour mid-flight), LRU eviction over unpinned slots, and a cold
  load is ONE donated jitted slot-write (`serving_adapter_load`, the
  `cow_block` pattern: the slot id rides as a traced scalar, so every
  load of every adapter reuses one executable — never a recompile).

TP composition (`serving.distributed.tp_engine`): A of column-parallel
projections (qkv, ffn1) replicates and B shards its out axis over
`mp` (the qkv B shard-major-permuted exactly like `qkv_w`); A of
row-parallel projections (out, ffn2) shards its IN axis so the delta
is a partial sum that joins the psum the mixed step already does; B
there replicates (`parallel.mp_layers.SERVING_LORA_TP_SPECS`).

KV interaction: LoRA changes the K/V a request writes, so the radix
prefix cache — which shares blocks by TOKEN ids alone — must never
share blocks across adapters. Requests with a non-null adapter simply
bypass the prefix cache (lookup and insert); base-model requests keep
full sharing. Preemption needs no special handling: the victim's
blocks are dropped and re-prefilled under the same adapter.

MoE stacks hook qkv + attention-out only (expert FFNs are routed,
capacity-sliced and possibly int4-packed — a per-token dense delta
there would double the dispatch machinery for little finetune signal;
attention LoRA is the standard high-signal target).
"""
from __future__ import annotations

import time

import numpy as np

from ..profiler import metrics as _pmetrics

#: hooked projection family names, in the fixed order the step
#: consumes their slot tensors (a/b interleaved per family)
DENSE_HOOKS = ("qkv", "out", "ffn1", "ffn2")
MOE_HOOKS = ("qkv", "out")


def hook_dims(decoder):
    """[(name, d_in, d_out)] for the decoder's hooked projections
    (full, unsharded dims — the TP engine shards the built arrays)."""
    D = decoder.embed_dim
    inner = decoder.num_heads * decoder.head_dim
    hooks = [("qkv", D, 3 * inner), ("out", inner, D)]
    if not int(getattr(decoder, "_num_experts", 0)):
        F = decoder.dim_feedforward
        hooks += [("ffn1", D, F), ("ffn2", F, D)]
    return hooks


class AdapterCache:
    """Fixed device slot tensors + host pin/LRU bookkeeping for K LoRA
    adapters served through one compiled mixed step.

    `max_adapters` counts slot 0 (the reserved null adapter), so
    `max_adapters - 1` finetunes can be RESIDENT at once; any number
    can be registered — cold ones load into an evicted slot on demand.
    """

    def __init__(self, decoder, *, max_adapters, rank, alpha=None,
                 dtype="float32", clock=time.monotonic):
        import jax.numpy as jnp
        K = int(max_adapters)
        if K < 2:
            raise ValueError(
                f"max_adapters={K} leaves no usable slot past the "
                "reserved null adapter (slot 0); need >= 2")
        r = int(rank)
        if r < 1:
            raise ValueError(f"lora_rank must be >= 1, got {r}")
        self.max_adapters = K
        self.rank = r
        self.alpha = float(alpha) if alpha is not None else float(r)
        self.scaling = self.alpha / r      # folded into B at load time
        self.clock = clock
        self.hooks = hook_dims(decoder)
        self.num_layers = decoder.num_layers
        self._dtype = jnp.dtype(dtype)
        L = self.num_layers
        self._arrays = {}
        for name, di, do in self.hooks:
            self._arrays[f"lora_{name}_a"] = jnp.zeros(
                (L, K, di, r), self._dtype)
            self._arrays[f"lora_{name}_b"] = jnp.zeros(
                (L, K, r, do), self._dtype)
        self.array_names = tuple(self._arrays)
        # host ledger: slot 0 is permanently the null adapter
        self._registry = {}                # adapter_id -> host weights
        self._resident = {}                # adapter_id -> slot
        self._slot_ids = [None] * K        # slot -> adapter_id
        self._pins = np.zeros(K, np.int64)
        self._stamp = np.zeros(K, np.float64)
        self._tick = 0
        # hooks a sharded engine installs (serving.distributed):
        # prepare(name, payload) re-lays a payload out for the mesh
        # (shard-major qkv B); place(cache) re-pins the canonical
        # shardings after the donated load write (the PR 8/PR 10
        # silent-recompile lesson, same as kv_cache.place_pools)
        self.prepare = None
        self.place = None
        self._load_fn = None
        # raw counters (always on; mirrored into the metrics registry
        # under the one-branch discipline when observability is on)
        self.cache_hits = 0
        self.cache_misses = 0
        self.evictions = 0
        self.load_seconds = 0.0

    # -------------------------------------------------------- inspection
    def device_arrays(self):
        """The slot tensors in `array_names` order — the engine feeds
        them to the mixed step every iteration."""
        return [self._arrays[n] for n in self.array_names]

    def known(self, adapter_id):
        return adapter_id in self._registry

    def resident(self, adapter_id):
        """True when the adapter sits in a device slot right now (the
        router's adapter-affinity signal). The null adapter is always
        resident."""
        return adapter_id is None or adapter_id in self._resident

    def slot_of(self, adapter_id):
        if adapter_id is None:
            return 0
        return self._resident.get(adapter_id)

    @property
    def resident_count(self):
        """Assigned (non-null) slots — the resident-adapters gauge."""
        return len(self._resident)

    def pin_count(self, adapter_id):
        slot = self._resident.get(adapter_id)
        return 0 if slot is None else int(self._pins[slot])

    @property
    def total_pins(self):
        return int(self._pins[1:].sum())

    @property
    def bytes_per_slot(self):
        """Marginal HBM one resident tenant costs: the per-slot slice
        of every A/B slot tensor. For rank r over the hooked
        projections this is Sigma r*(d_in + d_out)*L*itemsize — the
        `2*r*d*layers`-per-square-projection bound the bench asserts
        against."""
        item = self._dtype.itemsize
        return sum(self.rank * (di + do) * self.num_layers * item
                   for _, di, do in self.hooks)

    # ------------------------------------------------------ registration
    def register(self, adapter_id, weights):
        """Register a finetune's host weights. `weights` maps each
        hooked projection name to an `(a, b)` pair of arrays shaped
        `[L, d_in, r]` / `[L, r, d_out]` (numpy or jax). Registration
        is host-only — device slots are claimed lazily at admission."""
        if adapter_id is None:
            raise ValueError("adapter_id None is the reserved null "
                             "adapter; it needs no registration")
        got = set(weights)
        want = {n for n, _, _ in self.hooks}
        if got != want:
            raise ValueError(
                f"adapter {adapter_id!r} must provide exactly "
                f"{sorted(want)}, got {sorted(got)}")
        L, r = self.num_layers, self.rank
        host = {}
        for name, di, do in self.hooks:
            a, b = (np.asarray(x) for x in weights[name])
            if a.shape != (L, di, r) or b.shape != (L, r, do):
                raise ValueError(
                    f"adapter {adapter_id!r} {name}: want a "
                    f"{(L, di, r)} / b {(L, r, do)}, got "
                    f"{a.shape} / {b.shape}")
            host[name] = (a, b)
        self._registry[adapter_id] = host
        return adapter_id

    # --------------------------------------------------------- residency
    def _touch(self, slot):
        self._tick += 1
        self._stamp[slot] = self._tick

    def acquire(self, adapter_id):
        """Pin `adapter_id` into a device slot for one resident
        request. Returns the slot index, or None when every non-null
        slot is pinned by in-flight requests (the scheduler then
        leaves the request queued — admission blocks on residency,
        it never corrupts a neighbour's slot mid-flight)."""
        if adapter_id is None:
            return 0
        host = self._registry.get(adapter_id)
        if host is None:
            raise ValueError(f"adapter {adapter_id!r} is not "
                             "registered on this engine")
        slot = self._resident.get(adapter_id)
        if slot is not None:
            self._pins[slot] += 1
            self._touch(slot)
            self.cache_hits += 1
            if _pmetrics._enabled:
                from . import metrics as smetrics
                smetrics.SERVING_ADAPTER_CACHE_HITS.inc()
            return slot
        # cold: a free slot first, else the LRU unpinned slot
        evicted = False
        free = [s for s in range(1, self.max_adapters)
                if self._slot_ids[s] is None]
        if free:
            slot = free[0]
        else:
            cands = [s for s in range(1, self.max_adapters)
                     if self._pins[s] == 0]
            if not cands:
                return None
            slot = min(cands, key=lambda s: self._stamp[s])
            del self._resident[self._slot_ids[slot]]
            self._slot_ids[slot] = None
            self.evictions += 1
            evicted = True
        self.cache_misses += 1
        t0 = self.clock()
        self._load(slot, host)
        dt = self.clock() - t0
        self.load_seconds += dt
        self._slot_ids[slot] = adapter_id
        self._resident[adapter_id] = slot
        self._pins[slot] += 1
        self._touch(slot)
        if _pmetrics._enabled:
            from . import metrics as smetrics
            smetrics.SERVING_ADAPTER_CACHE_MISSES.inc()
            smetrics.SERVING_ADAPTER_LOAD_SECONDS.inc(max(dt, 0.0))
            if evicted:
                smetrics.SERVING_ADAPTER_EVICTIONS.inc()
            smetrics.SERVING_ADAPTERS_RESIDENT.set(self.resident_count)
        return slot

    def release(self, adapter_id):
        """Drop one resident request's pin (finish / preempt / expire
        / cancel / migrate-away). The adapter STAYS resident until LRU
        eviction needs its slot — the warm-cache property the router's
        adapter affinity banks on."""
        if adapter_id is None:
            return
        slot = self._resident.get(adapter_id)
        if slot is None or self._pins[slot] <= 0:
            raise ValueError(
                f"release of adapter {adapter_id!r} without a pin")
        self._pins[slot] -= 1

    # -------------------------------------------------------- device load
    def _load(self, slot, host):
        """Write one adapter's weights into `slot` across every hooked
        projection: ONE donated jitted executable
        (`serving_adapter_load`), slot id as a traced scalar — cold
        loads and evict-reloads all reuse it, so adapter churn can
        never recompile anything (budget-1 in
        `analysis.guards.DEFAULT_BUDGETS`)."""
        import jax.numpy as jnp

        if self._load_fn is None:
            from ..jit.functional import instrumented_jit
            n = len(self.array_names)

            def load(*args):
                arrs, slot_i, pays = args[:n], args[n], args[n + 1:]
                return tuple(a.at[:, slot_i].set(p)
                             for a, p in zip(arrs, pays))

            self._load_fn = instrumented_jit(
                load, "serving_adapter_load",
                donate_argnums=tuple(range(n)))
        payloads = []
        for name, _, _ in self.hooks:
            a, b = host[name]
            b = np.asarray(b, np.float64) * self.scaling  # fold alpha/r
            for kind, arr in (("a", a), ("b", b)):
                if self.prepare is not None:
                    arr = self.prepare(f"lora_{name}_{kind}", arr)
                payloads.append(jnp.asarray(
                    np.asarray(arr).astype(self._dtype)))
        out = self._load_fn(
            *[self._arrays[n] for n in self.array_names],
            jnp.int32(slot), *payloads)
        for name, arr in zip(self.array_names, out):
            self._arrays[name] = arr
        if self.place is not None:
            self.place(self)

    # ----------------------------------------------------------- metrics
    def hit_ratio(self):
        t = self.cache_hits + self.cache_misses
        return self.cache_hits / t if t else 0.0


def make_random_adapter(decoder, rank, seed=0, scale=0.02):
    """A deterministic random adapter for smokes/benches/examples:
    nonzero A and B for every hooked projection (so a wrong slot or a
    missed delta visibly changes tokens)."""
    rng = np.random.RandomState(seed)
    L = decoder.num_layers
    out = {}
    for name, di, do in hook_dims(decoder):
        a = rng.randn(L, di, rank).astype(np.float32) * scale
        b = rng.randn(L, rank, do).astype(np.float32) * scale
        out[name] = (a, b)
    return out
