"""ServingEngine — paged-KV continuous batching over the fused GPT stack.

One jitted **mixed step** serves a churning mix of requests: every
input is a fixed-shape slot tensor (flat token ids, positions, block
tables, per-slot sample indices), so admissions, completions,
preemptions and ragged prompt lengths never change a compiled shape —
the step compiles exactly ONCE per engine (asserted by
tests/test_serving.py via the PR 1 `instrumented_jit` compile counter).

The step runs the same math as `GPTForGeneration`'s compiled
prefill/decode (`incubate/nn/generation.py`) — same `_ln`/`_mm`/
`_qkv`/`_ffn_dense` cores from `incubate/nn/fused_transformer.py`,
attention through `ops.pallas.flash_attention.ragged_paged_attention`
— so serving output is token-identical to single-request
`generate()` for the same prompts (the parity test).

Host loop per `step()`:
  scheduler.plan()  →  pack_step()  →  jitted mixed step  →  sample
  bookkeeping (TTFT / inter-token metrics, EOS + length termination,
  block release).

MoE decoder stacks (`GPTForGeneration(moe=...)`) serve through the
same step: per-token top-k routing into FIXED expert-capacity slots
(`_ffn_moe_tokens` — T is the static token budget, so the [E, C, D]
dispatch buffers are compile-time shapes and capacity overflow
degrades to the residual path, never a recompile); per-expert token
counts / dropped totals / the balance-loss gauge ride the step
outputs (docs/MOE.md). `serving.distributed.TPServingEngine` adds
TP x EP sharding over a 2-D (ep, mp) mesh.

Disaggregated roles (docs/SERVING.md "Disaggregated serving"):
`role="prefill"` parks each request in the "handoff" state right after
its first sampled token — `extract_request` then exports its KV blocks
(int8 scale rows included) into a `MigrationTicket` a decode-role
engine admits mid-stream via `submit_migrated`, with greedy outputs
token-identical to a monolithic engine; `role="decode"` defaults to a
decode-sized token budget and admits migrated requests by IMPORTING
their blocks at scheduler admission (never a new compiled shape — the
one-compile contract holds across migration admits).

With `draft_k > 0` (greedy only) each decode feeds a verify group —
the last accepted token plus up to draft_k n-gram prompt-lookup
proposals (`serving.draft`) — through a fixed `[max_slots, draft_k+1]`
verify region scored by `verify_paged_attention`; the host accepts the
longest sequential-greedy prefix, emits 1..draft_k+1 tokens, and rolls
back KV blocks the rejected tail had claimed. Output stays
token-identical to `draft_k=0`, and the step still compiles exactly
once (docs/SERVING.md).
"""
from __future__ import annotations

import time

import numpy as np

from ..jit.functional import instrumented_jit
from ..profiler import metrics as _pmetrics
from . import batcher
from . import metrics as smetrics
from . import tracing as _tracing
from .batcher import SamplingConfig, pack_step, select_token
from .kv_cache import PagedKVCache
from .scheduler import Scheduler

STEP_FN_NAME = "serving_mixed_step"
SWAP_FN_NAME = "serving_weight_swap"

# default replica names (`role` + sequence): stable labels for trace
# span events and flight-recorder tracks when the caller names nothing
import itertools as _itertools  # noqa: E402
_ENGINE_SEQ = _itertools.count()


class ServingEngine:
    def __init__(self, model, *, max_slots=8, block_size=16,
                 num_blocks=None, max_seq_len=None, token_budget=None,
                 sampling=None, eos_token_id=None, cache_dtype=None,
                 kv_dtype=None, seed=0, clock=time.monotonic,
                 draft_k=0, draft_ngram=3, draft_ring=128,
                 penalty_vocab_bins=None, prefix_caching=False,
                 role="mixed", max_adapters=0, lora_rank=8,
                 lora_alpha=None, moe_weight_dtype=None,
                 sparse_blocks=None, sparse_recent=2,
                 track_summaries=None, name=None,
                 ticks_per_dispatch=1, multitick_async=None):
        import functools

        import jax
        import jax.numpy as jnp
        model.eval()
        self.model = model
        dec = model.decoder
        self.num_experts = int(getattr(dec, "_num_experts", 0))
        if self.num_experts and getattr(dec, "_ep_size", 1) > 1:
            raise ValueError(
                "serve a FULL MoE stack (ep_size=1): the engine shards "
                "experts itself (TPServingEngine expert_parallel=)")
        L, H, Dh = dec.num_layers, dec.num_heads, dec.head_dim
        maxpos = model.max_position_embeddings
        max_seq_len = min(max_seq_len or maxpos, maxpos)
        if block_size == "auto":
            # tuned KV block size (ISSUE 11): the kernel autotuner's
            # cached winner for this engine's shape bucket, falling
            # back to the hand-picked 16. Candidates are admitted
            # through the SAME alignment predicate as the serve-time
            # Pallas dispatch gate, so "auto" can never pick a block
            # size the kernels would refuse (bench.py's
            # kernel_autotune extra is what populates the cache).
            from ..ops.pallas import autotune as _kt

            from .kv_cache import KV_DTYPES, kv_jnp_dtype
            # quantized pools key the lookup by their storage dtype
            # (KV_DTYPES' quantized flag is the single source of
            # truth, not a re-hardcoded name list); float pools share
            # the fp32 key
            quant_bs = kv_dtype is not None and \
                KV_DTYPES.get(str(kv_dtype), (0, False))[1]
            block_size = _kt.ensure(
                "paged_block_size",
                _kt.shape_bucket(max_slots, H, Dh),
                np.dtype(kv_jnp_dtype(kv_dtype)) if quant_bs
                else np.dtype(np.float32),
                {"block_size": 16})["block_size"]
            # geometry clamp: a winner tuned under a longer context
            # must never exceed THIS engine's sequence bound (one
            # block spanning the whole sequence would degrade paging/
            # CoW/prefix sharing to whole-sequence granularity); the
            # candidate list shares the gate's alignment predicate
            allowed = [c["block_size"]
                       for c in _kt.paged_block_size_candidates(
                           Dh, max_seq_len)]
            if block_size not in allowed:
                block_size = 16 if 16 in allowed else allowed[-1]
        self.block_size = int(block_size)
        mbps = -(-max_seq_len // self.block_size)
        if num_blocks is None:
            # full residency for every slot, + the reserved null block
            num_blocks = max_slots * mbps + 1
        # disaggregated serving role (docs/SERVING.md): "prefill" runs
        # chunked prefill only — the request parks in the "handoff"
        # state after its first sampled token and the frontend extracts
        # it toward a decode replica; "decode" behaves like "mixed" at
        # the engine level (it can still re-prefill a preempted
        # migrant) but defaults to a decode-sized token budget. The
        # router's dispatch policy is what keeps fresh prompts off
        # decode replicas.
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r}")
        self.role = role
        # replica label stamped on trace span events + the flight
        # recorder track (serving.tracing, ISSUE 16)
        self.name = (str(name) if name is not None
                     else f"{role}{next(_ENGINE_SEQ)}")
        self.draft_k = int(draft_k)
        self.draft_ngram = int(draft_ngram)
        self.sampling = sampling or SamplingConfig()
        # config validation is LOUD (ISSUE 19): the silent draft_k
        # zeroing under penalized sampling is gone — penalties now
        # compose with speculation through per-position count priors
        # (docs/SERVING.md "Feature matrix"), so what remains invalid
        # is refused up front instead of quietly degraded
        if self.draft_k < 0:
            raise ValueError(f"draft_k={draft_k} must be >= 0")
        if self.draft_k > 0 and int(draft_ngram) < 1:
            raise ValueError(
                f"draft_ngram={draft_ngram} must be >= 1 with "
                "speculation on")
        self.draft_ring = int(draft_ring)
        if self.draft_k > 0 and self.draft_ring < 2:
            raise ValueError(
                f"draft_ring={draft_ring} must be >= 2 with "
                "speculation on (the n-gram scan needs at least one "
                "earlier token besides the tail)")
        # penalty count-histogram bins (ISSUE 19): the device-resident
        # [max_slots, Vb] token-count tensor the in-step logit
        # processors read; Vb defaults to the full vocab (exact HF
        # semantics), smaller Vb trades penalty precision for state
        # size via t % Vb binning (docs/SERVING.md)
        vocab = int(getattr(model, "vocab_size", 0) or 0)
        self._penalty_bins = (vocab if penalty_vocab_bins is None
                              else int(penalty_vocab_bins))
        if batcher.needs_history(self.sampling) \
                and self._penalty_bins < 1:
            raise ValueError(
                f"penalty_vocab_bins={penalty_vocab_bins} must be "
                ">= 1 with penalized sampling")
        # plain sampling (temperature/top-k/top-p) keeps speculation
        # via the standard REJECTION rule against the filtered target
        # distribution; penalized sampling composes too — the verify
        # head rebuilds each draft position's count prior from the
        # fed tokens, so every position is penalized by exactly the
        # context a 1-token-at-a-time engine would have seen. The
        # output DISTRIBUTION therefore matches draft_k=0 sampling,
        # and the greedy path keeps its exact token-identity verify.
        self.spec_sampling = (self.draft_k > 0
                              and self.sampling.strategy != "greedy")
        # retired fallback flag (pre-ISSUE 19 engines zeroed draft_k
        # under penalized sampling); kept as a constant for operators'
        # dashboards — `speculation_mode` below is the live signal
        self.speculation_disabled = False
        # device-resident multi-tick decode (docs/SERVING.md "Device-
        # resident decode"): with ticks_per_dispatch=N>1, pure-decode
        # dispatches run N ticks inside ONE lax.while_loop around the
        # mixed step — the host regains control only on per-slot
        # events (finish/overflow) or when the tick budget runs out.
        # "auto" sizes N per dispatch from measured step/host times
        # (staging width stays the fixed maximum, 8). N=1 keeps the
        # legacy single-tick path byte-for-byte.
        self._ticks_auto = ticks_per_dispatch == "auto"
        tp = 8 if self._ticks_auto else int(ticks_per_dispatch)
        if tp < 1:
            raise ValueError(
                f"ticks_per_dispatch={ticks_per_dispatch!r} must be "
                ">= 1 (or 'auto')")
        self.ticks_per_dispatch = tp
        # ISSUE 19: speculation and penalized sampling now run INSIDE
        # the device loop (on-device n-gram drafting from the token
        # ring + count-histogram penalties), so the PR 18 single-tick
        # fallbacks are gone — ticks_per_dispatch > 1 always takes
        # the while_loop path
        self._multitick = tp > 1
        # operator-visible speculation state (tools/metrics_dump.py):
        # off (draft_k=0) / host (1-tick host n-gram drafting) /
        # device (drafting traced into the multi-tick loop body)
        self.speculation_mode = (
            "off" if self.draft_k == 0
            else "device" if self._multitick else "host")
        if multitick_async is None:
            import os
            multitick_async = os.environ.get(
                "PADDLE_TPU_MULTITICK_ASYNC", "1") != "0"
        self._multitick_async = bool(multitick_async)
        # block-sparse paged decode attention (ISSUE 15, docs/
        # SERVING.md "Long-context serving"): with `sparse_blocks=B`,
        # every decode/verify query scores the slot's candidate blocks
        # against per-block channel-wise min/max key summaries
        # (Quest-style upper bound) and attends only a FIXED budget of
        # blocks — B top-scoring plus the first block (attention sink)
        # and a recency window of `sparse_recent` blocks (always
        # including the in-flight tail, widened so a K-wide verify
        # group's own writes are always resident). Fixed width means
        # fixed shapes: sparsity never recompiles, and `sparse_blocks
        # >= allocated blocks` is token-identical to the dense engine.
        if sparse_blocks == "auto":
            # tuned sparse budget (ISSUE 17 satellite): the smallest
            # block budget that met the >=99% needle-agreement floor
            # under `serving.sparse_budget.tune_sparse_budget`, keyed
            # by head geometry; a cold cache falls back to the
            # hand-picked 8 of docs/SERVING.md
            from ..ops.pallas import autotune as _kt
            tuned = _kt.ensure(
                "sparse_budget", _kt.shape_bucket(H, Dh),
                np.dtype(np.float32),
                {"sparse_blocks": 8,
                 "sparse_recent": int(sparse_recent)})
            sparse_blocks = tuned["sparse_blocks"]
            sparse_recent = tuned.get("sparse_recent", sparse_recent)
        self.sparse_blocks = (None if sparse_blocks is None
                              else int(sparse_blocks))
        self._sparse = self.sparse_blocks is not None
        self.sparse_table_width = 0
        self._sparse_recent = 0
        if self._sparse:
            if self.sparse_blocks < 1:
                raise ValueError(
                    f"sparse_blocks={sparse_blocks} must be >= 1 "
                    "(or None for dense decode attention)")
            K_w = self.draft_k + 1
            # the recency window must cover every block a verify
            # group's K fed tokens can span, so the group's own
            # just-written keys are always attended
            self._sparse_recent = max(
                int(sparse_recent),
                1 + -(-(K_w - 1) // self.block_size))
            self.sparse_table_width = min(
                mbps, 1 + self._sparse_recent + self.sparse_blocks)
        # `track_summaries=True` maintains the block summaries WITHOUT
        # the sparse decode region: the prefill-role half of a sparse
        # disaggregated fleet (docs/SERVING.md) — prefill runs at
        # dense speed paying only the append-side scatter, while its
        # exported blocks carry the summary rows a sparse decode
        # replica's kv_meta requires
        self._track_summaries = (self._sparse if track_summaries
                                 is None else bool(track_summaries))
        if self._sparse and not self._track_summaries:
            raise ValueError(
                "sparse_blocks needs the block summaries; don't pass "
                "track_summaries=False on a sparse engine")
        self.token_budget = batcher.choose_token_budget(
            max_slots, self.block_size, token_budget,
            verify_width=self.draft_k + 1, role=self.role,
            reserve_region=self._sparse)
        dtype = cache_dtype or getattr(model, "_gen_cache_dtype",
                                       "bfloat16")
        self.kv = PagedKVCache(
            L, H, Dh, num_blocks=num_blocks,
            block_size=self.block_size, max_slots=max_slots,
            max_blocks_per_slot=mbps, dtype=dtype, kv_dtype=kv_dtype,
            summaries=self._track_summaries)
        # radix prefix cache: cross-request KV reuse for shared prompt
        # heads (system prompts, few-shot templates, chat history) —
        # registers itself as the kv cache's eviction backstop
        self.prefix_cache = None
        if prefix_caching:
            from .prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(self.kv)
        # multi-LoRA adapter slots (ISSUE 14, docs/SERVING.md
        # "Multi-tenant serving"): fixed [L, K, ...] slot tensors per
        # hooked projection ride the mixed step as inputs; the host
        # cache pins/evicts/loads without ever changing a compiled
        # shape. The compute dtype matches the step's so deltas cast
        # once.
        cdt_name = getattr(model, "_compute_dtype", "float32")
        self.adapters = None
        if int(max_adapters):
            from .adapters import AdapterCache
            self.adapters = AdapterCache(
                dec, max_adapters=int(max_adapters),
                rank=int(lora_rank), alpha=lora_alpha,
                dtype=cdt_name, clock=clock)
        from .draft import ngram_propose

        def _windowed_draft(tokens, _k=self.draft_k,
                            _ng=int(draft_ngram), _w=self.draft_ring):
            # the host proposer scans the SAME trailing window the
            # device ring holds, so a 1-tick host-drafting engine and
            # an N-tick device-drafting one propose identically —
            # the token-identity contract of the spec matrix tests
            return ngram_propose(tokens[-_w:], _k, max_ngram=_ng)

        self.scheduler = Scheduler(
            self.kv, max_slots=max_slots,
            token_budget=self.token_budget, clock=clock,
            draft_k=self.draft_k,
            draft_fn=_windowed_draft,
            device_draft=self._multitick and self.draft_k > 0,
            prefix_cache=self.prefix_cache,
            adapter_cache=self.adapters,
            reserve_region=self._sparse)
        self.scheduler.replica = self.name
        self.eos_token_id = eos_token_id
        self.clock = clock
        self._rng = jax.random.PRNGKey(int(seed))
        # cast float params to the compute dtype ONCE (same discipline
        # as generation.generate: a per-step astype re-reads the full
        # parameter set every token)
        cdt = jnp.dtype(cdt_name)
        self._arrays = [a.astype(cdt)
                        if a.dtype in (jnp.float32, jnp.float64) else a
                        for a in (t._data for t in model._gen_tensors())]
        # the engine owns its decoder-param NAME list (a copy of the
        # model's): engine-side expert quantization below may extend
        # it with scale entries the float model never had
        self._names = list(model._dec_names)
        # engine-side weight-only expert quantization (ISSUE 14):
        # serve a float/bf16 MoE stack with int8 or packed-int4
        # experts without rebuilding the model — the expert arrays in
        # self._arrays are quantized in place and the step cfg carries
        # the matching moe_quant_bits
        self.moe_weight_dtype = moe_weight_dtype
        self._moe_weight_bits = 0
        if moe_weight_dtype is not None:
            self._quantize_moe_experts(str(moe_weight_dtype))
        # quantized pools donate their scale arrays and summary-
        # tracking pools their min/max rows alongside the K/V pools,
        # so every in-step pool write aliases in place
        donate = tuple(range(1, 1 + len(self.kv._pools())))
        step_fn = self._build_step()
        if self._multitick:
            # the while_loop wraps the RESULT of _build_step (for the
            # TP engine that's the shard_map'ed body, so the loop sits
            # OUTSIDE the mesh partitioning) and shares the single
            # serving_mixed_step compile budget: n_ticks is a traced
            # scalar, so mixed 1-tick and pure-decode N-tick
            # dispatches run the same executable
            step_fn = self._build_multitick(step_fn)
        self._step_fn = instrumented_jit(
            step_fn, STEP_FN_NAME, donate_argnums=donate)
        # multi-tick host runtime state: double-buffered plan tensors
        # (pack k+1 while k's may still be in flight), the deferred
        # observability lane (dispatch k's metrics/flight flush after
        # dispatch k+1 launches), and the measured-time EMAs the
        # "auto" tick heuristic sizes dispatches from
        self._plan_buffers = None
        if self._multitick:
            self._plan_buffers = (
                batcher.PlanBuffers(self.token_budget, max_slots),
                batcher.PlanBuffers(self.token_budget, max_slots))
        self._plan_flip = 0
        self._deferred = None
        self._tick_ema = None        # seconds per device tick
        self._gap_ema = None         # host seconds between dispatches
        self._last_harvest = None
        self.dispatches_run = 0
        self.device_ticks_run = 0
        self.host_stall_total = 0.0
        self.early_exit_counts = {"finish": 0, "overflow": 0,
                                  "reject": 0}
        # host mirrors of the cumulative draft economics (both the
        # host-drafting 1-tick path and the device loop's spec stats
        # fold in here; bench/smoke contracts read them directly)
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        if _pmetrics._enabled:
            # operators see WHY a replica is or isn't speculating:
            # exactly one mode label reads 1 (tools/metrics_dump.py)
            for m in ("off", "host", "device"):
                smetrics.SERVING_SPECULATION_STATE.labels(m).set(
                    1.0 if m == self.speculation_mode else 0.0)
        # fleet control plane (ISSUE 17): checkpoint version label
        # (rides router_requests_total + trace spans) and the ONE
        # jitted budget-1 weight-swap cast shared by every rolling-
        # upgrade flip on this engine (built lazily on first swap)
        self.weights_version = "v0"
        self._swap_fn = None
        # register this engine's paged-kernel shape buckets with the
        # autotuner (ISSUE 11): keys derive from the token budget /
        # slot count / per-shard head slice, so the tuner-cache audit
        # (tools/kernel_coverage.py --tuner-audit) can flag buckets
        # serving traffic hits that hold no tuned entry. Pure host
        # dict probes — the step itself is untouched.
        self._kernel_buckets = self._note_kernel_buckets()
        self._preempt_seen = 0
        self._prefix_seen = (0, 0, 0)    # hit / miss / evicted deltas
        self._imported_seen = 0          # kv.blocks_imported delta
        self.steps_run = 0
        # block-sparse decode accounting (host mirrors of the fixed
        # selection arithmetic — the per-step selected count is
        # min(allocated, sparse_table_width) by construction, so the
        # metrics need no extra device readback)
        self.sparse_candidate_blocks = 0
        self.sparse_selected_blocks = 0
        self._sparse_skip_seen = 0       # metrics-counter delta base
        # cumulative MoE routing state (host mirrors of the per-step
        # device stats; the smoke contracts read these directly)
        self.moe_expert_counts = np.zeros(max(self.num_experts, 1),
                                          np.float64)
        self.moe_dropped_total = 0.0
        self.moe_last_aux = 0.0
        # per-engine step flight recorder (serving.tracing): one host
        # record per step, noted only while tracing is enabled;
        # registered so profiler chrome export / summary() merge it
        self.flight = _tracing.StepFlightRecorder(self.name, self.role)
        _tracing.register_flight_recorder(self.flight)

    def _flight_extra(self):
        """Extra per-step flight-recorder fields; TPServingEngine
        overrides to stamp its mesh split."""
        return {}

    def _quantize_moe_experts(self, dtype_str):
        """Quantize the expert FFN stacks of `self._arrays` in place
        (weight-only int8, or nibble-packed int4 with fp16 scales) and
        extend `self._names` with the scale entries. Host-side, once,
        at build — the mixed step then reads int8/int4 expert bytes
        from HBM and dequantizes at the matmul (grouped kernel or
        einsum path alike). Refused on non-MoE stacks and on models
        that are already weight-only (requantizing int8 -> int4 would
        compound quantization error silently)."""
        import jax.numpy as jnp

        from ..incubate.nn.fused_transformer import \
            _quantize_expert_stack
        if dtype_str not in ("int8", "int4"):
            raise ValueError(
                f"moe_weight_dtype={dtype_str!r} not supported; use "
                "'int8' or 'int4'")
        if not self.num_experts:
            raise ValueError(
                "moe_weight_dtype needs a MoE decoder stack")
        if "ffn1_s" in self._names or "ffn2_s" in self._names:
            raise ValueError(
                "model experts are already weight-only quantized; "
                "build the float model and let the engine quantize, "
                "or pick the dtype at model build "
                "(FusedMultiTransformerMoeWeightOnly(moe_quant_bits=))")
        bits = 4 if dtype_str == "int4" else 8
        for wname in ("ffn1_w", "ffn2_w"):
            i = self._names.index(wname)
            w = self._arrays[2 + i]            # [L, E, In, Out]
            q, s = _quantize_expert_stack(
                jnp.asarray(w).astype(jnp.float32), bits)
            self._arrays[2 + i] = q
            sname = wname[:-2] + "_s"
            self._names.insert(i + 1, sname)
            self._arrays.insert(2 + i + 1, s)
        self._moe_weight_bits = bits

    def _note_kernel_buckets(self):
        """The (kernel, shape-bucket, dtype) keys this engine's mixed
        step resolves tuned configs under — one `kernel_config` probe
        each (recording cache hits/misses + the audit trail). The
        bucket derives from the token budget: with speculation the
        verify region [S, K] rides `paged_verify` and the remaining
        flat tokens `paged_ragged`; without, the whole [T] axis is one
        ragged bucket. Head counts are the PER-SHARD slice under TP
        (`_step_cfg`), so a TP=2 engine tunes different keys than
        TP=1 — topology is part of the key by construction, alongside
        the backend/device-count component `autotune.backend_key`
        already carries."""
        from ..ops.pallas import autotune as _kt
        cfg = self._step_cfg()
        H, Dh, BS = cfg.num_heads, cfg.head_dim, self.block_size
        # key by the POOL dtype (int8 pools are int8, fp8 pools
        # float8_e4m3fn, fp pools their own dtype) — exactly what the
        # kernels' trace-time lookups resolve under
        dt = self.kv.k_pool.dtype
        T, S, K = self.token_budget, self.kv.max_slots, self.draft_k + 1
        dtn = np.dtype(dt).name
        keys = []
        if self._sparse:
            # the decode/verify region reads the SHORTENED tables: its
            # bucket carries the table width (sparse_table_width) so a
            # sparse winner can never alias a dense one
            keys.append(("paged_sparse",
                         _kt.shape_bucket(S, K, H, Dh, BS,
                                          self.sparse_table_width),
                         dtn))
            keys.append(("paged_ragged",
                         _kt.shape_bucket(max(T - S * K, 1), 1, H, Dh,
                                          BS), dtn))
        elif K > 1:
            keys.append(("paged_verify",
                         _kt.shape_bucket(S, K, H, Dh, BS), dtn))
            keys.append(("paged_ragged",
                         _kt.shape_bucket(max(T - S * K, 1), 1, H, Dh,
                                          BS), dtn))
        else:
            keys.append(("paged_ragged",
                         _kt.shape_bucket(T, 1, H, Dh, BS), dtn))
        for kernel, bucket, dtype in keys:
            # ensure(): a hit is one dict probe; a miss falls back to
            # the hand defaults — except under
            # PADDLE_TPU_KERNEL_AUTOTUNE=tune, where the registered
            # search runs HERE, at build time, before the step is ever
            # traced (the tuning-outside-the-jitted-step contract),
            # and persists the winner for every later engine
            _kt.ensure(kernel, bucket, dtype, default=None)
        return keys

    # ------------------------------------------------------- mixed step
    def _step_cfg(self):
        """The decoder config the step body runs under. The TP engine
        (`serving.distributed.tp_engine`) overrides this with the
        per-shard head count and an `mp_axis`, and `_step_body` then
        emits the matching psums — same math, sharded. Engine-side
        expert quantization overrides the cfg's moe bits so `_deq`/
        the grouped kernel dequantize what the engine actually packed."""
        import dataclasses
        cfg = self.model.decoder._cfg()
        if self._moe_weight_bits:
            cfg = dataclasses.replace(
                cfg, moe_quant_bits=self._moe_weight_bits)
        return cfg

    def _build_step(self):
        return self._step_body(self._step_cfg())

    def _step_body(self, cfg):
        import jax
        import jax.numpy as jnp

        from ..incubate.nn.fused_transformer import (
            _ffn_dense, _ffn_moe_tokens, _ln, _lora_delta, _maybe_psum,
            _mm, _qkv)
        from ..ops.pallas.flash_attention import (
            ragged_paged_attention, verify_paged_attention)

        from .kv_cache import FP8_MAX, SUMMARY_INIT, kv_jnp_dtype

        model = self.model
        names = list(self._names)
        L = cfg.num_layers
        BS = self.block_size
        T = self.token_budget
        S = self.kv.max_slots
        K = self.draft_k + 1          # verify width (1 = no speculation)
        sparse = self._sparse
        track = self._track_summaries  # summaries maintained on append
        Bt = self.sparse_table_width  # shortened table width (sparse)
        W_rec = self._sparse_recent   # forced recency window (blocks)
        MB = self.kv.max_blocks_per_slot
        # the reserved per-slot region: speculation reshapes it to
        # [S, K] for the verify entry; block-sparse decode reserves it
        # even at K == 1 so the selection is one fixed [S, ...] batch
        region_on = K > 1 or sparse
        R = S * K                     # region width when region_on
        sc = self.sampling
        quant = self.kv.quantized
        fp8 = self.kv.kv_dtype == "fp8_e4m3"
        use_hist = batcher.needs_history(sc)
        Vb = self._penalty_bins       # penalty count-histogram bins
        moe = cfg.num_experts > 0
        spec_sampling = self.spec_sampling
        lora = self.adapters is not None
        ad_names = tuple(self.adapters.array_names) if lora else ()
        K_ad = self.adapters.max_adapters if lora else 0

        def quantize(x):
            """[T, H, Dh] fp -> (quantized values, [T, H] fp32
            scales): symmetric per-token-per-head amax scaling — to
            the int8 grid, or to the fp8 e4m3 finite range (scaling
            amax onto 448 spends the format's whole mantissa budget
            per entry; the clip keeps boundary values off the NaN
            cast). A pure function of the token's own K/V, so
            quantization is independent of append order, chunking and
            block sharing (the property the prefix-cache/preemption
            parity tests rely on)."""
            xf = x.astype(jnp.float32)
            if fp8:
                s = jnp.max(jnp.abs(xf), axis=-1) / FP8_MAX
                qv = xf / jnp.maximum(s, 1e-20)[..., None]
                qv = jnp.clip(qv, -FP8_MAX, FP8_MAX)
                return qv.astype(kv_jnp_dtype("fp8_e4m3")), s
            s = jnp.max(jnp.abs(xf), axis=-1) / 127.0
            q8 = jnp.round(xf / jnp.maximum(s, 1e-20)[..., None])
            return jnp.clip(q8, -127, 127).astype(jnp.int8), s

        def select_blocks(q_r, pos_r, block_tables, smin_l, smax_l):
            """Top-B block selection for the decode/verify region
            (ISSUE 15, Quest-style): score every candidate block of
            each slot by the channel-wise upper bound of q . k over
            the block's [min, max] summary box, force-keep the first
            block (attention sink) and the last `W_rec` blocks (the
            recency window — which always covers the group's own
            just-written keys), take the fixed top `Bt`, and emit

              * a SHORTENED `[S, Bt]` block table (selected blocks in
                their original order; NULL-padded when a slot holds
                fewer than Bt blocks), and
              * COMPACTED query positions `[S, K]` — each query's
                position translated into the shortened table's
                coordinates, so the kernels' `key_pos <= query_pos`
                mask stays exactly right: full selected blocks before
                the query's own block are wholly visible, the query's
                block is visible up to its true offset, and the NULL
                padding columns (compacted positions past the query)
                are never read through.

            With Bt >= the slot's allocated blocks the selection is
            the identity (same table prefix, same positions), which is
            what makes `sparse_blocks >= allocated` bit-identical to
            the dense engine.

            q_r [S, K, H, Dh] raw queries; pos_r [S, K] true
            positions; smin_l/smax_l [NB, H, Dh] this layer's
            summaries."""
            from ..incubate.nn.fused_transformer import _maybe_psum
            qf = q_r.astype(jnp.float32)
            qpos = jnp.maximum(qf, 0.0)
            qneg = jnp.minimum(qf, 0.0)
            bt_r = block_tables[:S]                     # [S, MB]
            sming = smin_l[bt_r]                        # [S, MB, H, Dh]
            smaxg = smax_l[bt_r]
            # ub(q, block) = sum_d max(q_d*min_d, q_d*max_d)
            #             = sum_d (max(q_d,0)*max_d + min(q_d,0)*min_d)
            # summed over heads: under TP each shard holds its head
            # slice, so the psum makes every shard select from the
            # GLOBAL head total — TP=2 selections match TP=1 exactly.
            # The psum must come BEFORE the max over the group's K
            # queries: max_k(a_k + b_k) != max_k(a_k) + max_k(b_k)
            # when different queries achieve each shard's maximum, so
            # a post-max psum would make TP=2 rank blocks differently
            # than TP=1 whenever speculation meets real sparsity
            score = (jnp.einsum("skhd,smhd->skm", qpos, smaxg)
                     + jnp.einsum("skhd,smhd->skm", qneg, sming))
            score = _maybe_psum(cfg, score)             # [S, K, MB]
            score = jnp.max(score, axis=1)              # [S, MB]
            n_blk = jnp.max(pos_r, axis=1) // BS + 1    # [S] allocated
            m_idx = jnp.arange(MB, dtype=jnp.int32)[None, :]
            forced = (m_idx == 0) | (m_idx >= (n_blk - W_rec)[:, None])
            score = jnp.where(forced, jnp.float32(jnp.inf), score)
            # candidates past the allocated prefix can never be
            # selected, whatever their (stale) summaries say
            score = jnp.where(m_idx < n_blk[:, None], score,
                              -jnp.float32(jnp.inf))
            _, sel = jax.lax.top_k(score, Bt)           # [S, Bt]
            selv = jnp.take_along_axis(score, sel, axis=1)
            # re-sort the selection into original table order (the
            # compaction below depends on it); slots with fewer than
            # Bt valid blocks sort their -inf picks to the end as MB
            ord_ = jnp.sort(jnp.where(selv > -jnp.float32(jnp.inf),
                                      sel, MB), axis=1)
            short_bt = jnp.where(
                ord_ < MB,
                jnp.take_along_axis(bt_r, jnp.minimum(ord_, MB - 1),
                                    axis=1),
                0).astype(jnp.int32)
            bq = pos_r // BS                            # [S, K]
            cnt = jnp.sum(ord_[:, None, :] < bq[:, :, None], axis=-1)
            pos_c = (cnt * BS + pos_r % BS).astype(jnp.int32)
            return short_bt, pos_c

        def step(arrays, k_pool, v_pool, *rest):
            # static signature variants (one compile each way):
            # quantized pools add (k_scale, v_scale) after the pools
            # and summary-tracking pools (k_sum_min, k_sum_max) after
            # those — the kv_cache._pools() order; adapter slot
            # tensors follow them, with the per-token adapter ids
            # after sample_index; active logit processors add the
            # [S, Vb] token-count histogram before the rng (ISSUE 19:
            # the count form replaces the [S, W] history tensor so
            # the multi-tick loop can advance it per accepted token)
            rest = list(rest)
            k_scale = v_scale = counts = None
            k_sum_min = k_sum_max = None
            if quant:
                k_scale, v_scale = rest[:2]
                rest = rest[2:]
            if track:
                k_sum_min, k_sum_max = rest[:2]
                rest = rest[2:]
            ad_arrays = ()
            if lora:
                ad_arrays = rest[:len(ad_names)]
                rest = rest[len(ad_names):]
            (token_ids, slot_ids, positions, block_tables,
             sample_index) = rest[:5]
            rest = rest[5:]
            adapter_ids = rest.pop(0) if lora else None
            if use_hist:
                counts = rest.pop(0)
            (rng,) = rest
            n_dec = len(names)
            we, pe = arrays[0], arrays[1]
            dec_arrays = arrays[2:2 + n_dec]
            lnw, lnb, head = arrays[-3], arrays[-2], arrays[-1]
            params = dict(zip(names, dec_arrays))
            if lora:
                # the [L, K, ...] slot tensors join the scanned params
                # so each layer's xs slice carries its own adapter
                # rows; ONE [T, K] one-hot feeds every layer's deltas
                params.update(dict(zip(ad_names, ad_arrays)))
                lora_oh = jax.nn.one_hot(adapter_ids, K_ad,
                                         dtype=jnp.float32)
            else:
                lora_oh = None
            valid = slot_ids >= 0
            pos = jnp.where(valid, positions, 0)
            x = model._embed(we, pe, token_ids, pos)          # [T, D]
            safe_slot = jnp.where(valid, slot_ids, 0)
            # padding tokens write into the reserved NULL block
            wb = jnp.where(valid, block_tables[safe_slot, pos // BS], 0)
            wo = pos % BS

            def layer(carry, xs):
                at = 3
                h, kp, vp = carry[:3]
                ksc = vsc = smin = smax = None
                if quant:
                    ksc, vsc = carry[at:at + 2]
                    at += 2
                if track:
                    smin, smax = carry[at:at + 2]
                    at += 2
                ms = carry[-1] if moe else None
                pl, li = xs
                hn = _ln(h, pl["ln_s"], pl["ln_b"], cfg.epsilon)
                q, k, v = _qkv(cfg, pl, hn[None], lora_oh=lora_oh)
                q, k, v = q[0], k[0], v[0]                  # [T, H, Dh]
                if quant:
                    # quantize-on-append: int8/fp8 payload + per-entry
                    # scales land at the same (block, offset) coords
                    kq, ks_new = quantize(k)
                    vq, vs_new = quantize(v)
                    kp = kp.at[li, wb, wo].set(kq)
                    vp = vp.at[li, wb, wo].set(vq)
                    ksc = ksc.at[li, wb, wo].set(ks_new)
                    vsc = vsc.at[li, wb, wo].set(vs_new)
                    ks_l, vs_l = ksc[li], vsc[li]
                else:
                    kp = kp.at[li, wb, wo].set(k.astype(kp.dtype))
                    vp = vp.at[li, wb, wo].set(v.astype(vp.dtype))
                    ks_l = vs_l = None
                if track:
                    # summary update on append: the offset-0 write of
                    # a block RESETS its row first (non-first tokens
                    # aim the reset at the NULL row), then one
                    # scatter-min/max folds every appended key in —
                    # well-defined even when one prefill chunk writes
                    # many entries of the same block, and a freed-
                    # then-reused block can never leak its previous
                    # owner's statistics
                    ksf = k.astype(jnp.float32)
                    rb = jnp.where(valid & (wo == 0), wb, 0)
                    smin = smin.at[li, rb].set(SUMMARY_INIT)
                    smax = smax.at[li, rb].set(-SUMMARY_INIT)
                    wbs = jnp.where(valid, wb, 0)
                    smin = smin.at[li, wbs].min(ksf)
                    smax = smax.at[li, wbs].max(ksf)
                if sparse:
                    # region queries attend the SHORTENED tables: the
                    # kernels read Bt blocks per slot instead of the
                    # whole context, and the compacted positions keep
                    # the causal mask exact; prefill chunks (whose
                    # queries sit mid-prompt) keep the dense path
                    q_r = q[:R].reshape(S, K, cfg.num_heads,
                                        cfg.head_dim)
                    pos_r = pos[:R].reshape(S, K)
                    short_bt, pos_c = select_blocks(
                        q_r, pos_r, block_tables, smin[li], smax[li])
                    if K == 1:
                        ar = ragged_paged_attention(
                            q[:R], kp[li], vp[li], short_bt,
                            slot_ids[:R], pos_c[:, 0], ks_l, vs_l,
                            kernel_name="paged_sparse")
                    else:
                        ar = verify_paged_attention(
                            q_r, kp[li], vp[li], short_bt,
                            jnp.arange(S, dtype=jnp.int32), pos_c,
                            ks_l, vs_l,
                            kernel_name="paged_sparse").reshape(
                            R, cfg.num_heads, cfg.head_dim)
                    ap = ragged_paged_attention(
                        q[R:], kp[li], vp[li], block_tables,
                        slot_ids[R:], pos[R:], ks_l, vs_l)
                    attn = jnp.concatenate(
                        [ar.reshape(R, cfg.num_heads, cfg.head_dim),
                         ap], axis=0)
                elif K == 1:
                    attn = ragged_paged_attention(
                        q, kp[li], vp[li], block_tables, slot_ids, pos,
                        ks_l, vs_l)
                else:
                    # the fixed verify region (slot s owns flat tokens
                    # [s*K, (s+1)*K)) runs through the verify-shaped
                    # entry — ONE block-table gather per slot instead of
                    # one per flat token; prefill chunks keep the
                    # flat-token ragged path
                    qv = q[:R].reshape(S, K, cfg.num_heads, cfg.head_dim)
                    av = verify_paged_attention(
                        qv, kp[li], vp[li], block_tables,
                        jnp.arange(S, dtype=jnp.int32),
                        pos[:R].reshape(S, K), ks_l, vs_l)
                    ap = ragged_paged_attention(
                        q[R:], kp[li], vp[li], block_tables,
                        slot_ids[R:], pos[R:], ks_l, vs_l)
                    attn = jnp.concatenate(
                        [av.reshape(R, cfg.num_heads, cfg.head_dim),
                         ap], axis=0)
                attn = attn.reshape(T, cfg.num_heads * cfg.head_dim)
                out = _mm(cfg, attn, pl["out_w"], pl.get("out_s"))
                if lora_oh is not None:
                    # row-parallel LoRA: A holds this shard's head
                    # slice of the in axis, so the delta is a partial
                    # product that joins the psum right below
                    out = out + _lora_delta(attn, pl["lora_out_a"],
                                            pl["lora_out_b"], lora_oh)
                # row-parallel reduction under TP (no-op when
                # cfg.mp_axis is None): each shard holds the partial
                # product of its own head slice; _ffn_dense below does
                # the same for its row-parallel ffn2
                out = _maybe_psum(cfg, out)
                out = out + pl["out_b"].astype(out.dtype)
                h = h + out
                hn = _ln(h, pl["ffn_ln_s"], pl["ffn_ln_b"], cfg.epsilon)
                if moe:
                    # per-token top-k routing into fixed capacity slots
                    # (padding tokens masked out by `valid`); overflow
                    # rides the residual — shapes never change, so the
                    # one-compile rule holds with MoE exactly as dense
                    f, st = _ffn_moe_tokens(cfg, pl, hn, valid)
                    h = h + f
                    ms = jax.tree.map(jnp.add, ms, st)
                else:
                    h = h + _ffn_dense(cfg, pl, hn, lora_oh=lora_oh)
                new_carry = (h, kp, vp)
                if quant:
                    new_carry += (ksc, vsc)
                if track:
                    new_carry += (smin, smax)
                if moe:
                    new_carry += (ms,)
                return new_carry, None

            carry0 = (x, k_pool, v_pool)
            if quant:
                carry0 += (k_scale, v_scale)
            if track:
                carry0 += (k_sum_min, k_sum_max)
            if moe:
                carry0 += ({"counts": jnp.zeros((cfg.num_experts,),
                                                jnp.float32),
                            "dropped": jnp.zeros((), jnp.float32),
                            "aux": jnp.zeros((), jnp.float32)},)
            carry, _ = jax.lax.scan(layer, carry0,
                                    (params, jnp.arange(L)))
            moe_stats = carry[-1] if moe else None
            if moe:
                # aux reported as the per-layer mean balance loss
                moe_stats = dict(moe_stats,
                                 aux=moe_stats["aux"] / float(L))
            n_pool = 2 + (2 if quant else 0) + (2 if track else 0)
            x = carry[0]
            pools = tuple(carry[1:1 + n_pool])
            if moe:
                pools += (moe_stats,)
            xf = _ln(x, lnw, lnb, cfg.epsilon)
            sidx = jnp.clip(sample_index, 0, T - 1)
            h_last = xf[sidx]                          # [max_slots, D]
            logits = jnp.matmul(h_last, head.astype(h_last.dtype))
            if spec_sampling:
                rng, rng_u, rng_res, rng_bonus = jax.random.split(
                    rng, 4)
            tok = select_token(logits, rng, sc, counts=counts)
            if K == 1:
                return (tok,) + pools
            hv = xf[:R].reshape(S, K, -1)
            logits_v = jnp.matmul(hv, head.astype(hv.dtype))
            lv = logits_v.astype(jnp.float32)
            fed = token_ids[:R].reshape(S, K)
            if use_hist:
                # per-position count PRIORS (ISSUE 19): verify
                # position j scores the context [.., fed[0..j]];
                # fed[0] (the last accepted token) is already in the
                # base histogram, so the prior adds the running count
                # of fed[1..j] — each draft position is penalized by
                # exactly the context a 1-token engine would have seen
                inc = jax.nn.one_hot(fed[:, 1:] % Vb, Vb,
                                     dtype=jnp.float32)
                prior = counts.astype(jnp.float32)[:, None, :] \
                    + jnp.concatenate(
                        [jnp.zeros((S, 1, Vb), jnp.float32),
                         jnp.cumsum(inc, axis=1)], axis=1)
                lv = batcher.apply_count_penalties(lv, prior, sc)
            if not spec_sampling:
                # greedy scores for EVERY verify-region position:
                # tok_v[s, j] is the model's next token after slot s's
                # j-th fed token — the host accepts the longest draft
                # prefix matching it
                tok_v = jnp.argmax(lv, axis=-1).astype(jnp.int32)
                return ((tok, tok_v),) + pools
            # REJECTION-SAMPLING verify (ISSUE 11 satellite): the
            # n-gram proposer is deterministic (a point-mass draft
            # distribution q), so the standard rule reduces to:
            # accept draft d at position j w.p. min(1, p_j(d)) where
            # p_j = softmax(filter_logits(...)) is EXACTLY the
            # distribution non-speculative sampling draws from; on
            # rejection, emit a sample of the residual
            # norm(max(p_j - q, 0)) = p_j with d removed; when every
            # draft is accepted the bonus token samples the full p at
            # the last fed position. Emitted tokens are therefore
            # p-distributed at every position — the output
            # DISTRIBUTION matches draft_k=0 sampling.
            fl = batcher.filter_logits(lv, sc)          # [S, K, V]
            # fed token at position j+1, scored by position j (last
            # column pads with 0 — the host never reads its verdict)
            nxt = jnp.concatenate(
                [fed[:, 1:], jnp.zeros((S, 1), jnp.int32)], axis=1)
            probs = jax.nn.softmax(fl, axis=-1)
            p_draft = jnp.take_along_axis(
                probs, nxt[..., None], axis=-1)[..., 0]  # [S, K]
            u = jax.random.uniform(rng_u, (S, K))
            acc = u < p_draft
            # residual resample: p with the rejected draft removed
            res_mask = jax.nn.one_hot(nxt, fl.shape[-1],
                                      dtype=jnp.bool_)
            tok_res = jax.random.categorical(
                rng_res, jnp.where(res_mask, -1e9, fl),
                axis=-1).astype(jnp.int32)
            tok_v = jax.random.categorical(
                rng_bonus, fl, axis=-1).astype(jnp.int32)
            return ((tok, tok_v, tok_res, acc),) + pools

        return step

    def _build_multitick(self, base_step):
        """Wrap the one-tick mixed step in a `lax.while_loop` that runs
        up to `n_ticks` decode ticks per host dispatch (docs/SERVING.md
        "Device-resident decode").

        Call signature = the legacy step's, with the control tail
        appended AFTER the rng (params stay arg 0, donated pools stay
        1..n, so donation and the AOT export path are untouched):

            ..., rng, n_ticks, eos [S], remain [S], cap [S][, slot_ad]

        `rng` is now the CHAIN key — the loop performs the exact
        `rng, sub = split(rng)` the legacy host loop does before each
        step, once per executed tick, and returns the advanced chain,
        so an N-tick dispatch consumes the identical subkey sequence N
        legacy steps would (seeded-sampling token identity).

        Tick 0 consumes the host-packed plan arrays verbatim (bit-
        identity with the single-tick dispatch); ticks >= 1 rebuild
        the pure-decode inputs by scattering each live slot's previous
        token at its pack-time anchor (`sample_index` — the dense
        layout's packed index, the sparse region's own slot index),
        which reproduces exactly what the host packer would have built
        for the next step. The loop exits at the FIRST per-slot event
        so scheduling decisions (admission, preemption, expiry) happen
        at the same sequence boundaries a 1-tick engine would see.

        With speculation (`draft_k > 0`, ISSUE 19) the tail further
        appends the per-slot token RING (`ring [S, draft_ring]`,
        `rcnt [S]` — circular, token t at column t % draft_ring) and
        every tick widens to a verify group: the `jnp` n-gram drafter
        (`serving.draft.ngram_propose_device`) proposes from the ring,
        the verify head scores the group, the accept-length roll +
        bonus/residual token and the ring/count updates all happen
        in-loop — the multiplicative win (accept length x ticks per
        host round-trip) without a single host escape. Penalized
        sampling threads its `[S, penalty_vocab_bins]` count histogram
        through the carry the same way.

        Outputs replace the token head with the control block
        `(staged [S, N*K], counts [S], events [S], ticks, rng[,
        spec_proposed, spec_accepted, accept_hist [K]])`:
        `staged` is the -1-padded token staging buffer, `events` the
        per-slot bitmask (1 = finish: EOS or horizon; 2 = overflow:
        next tick would exceed the preallocated block capacity `cap`).
        Pools (and summed MoE stats) follow as before."""
        import jax
        import jax.numpy as jnp

        from .draft import ngram_propose_device, ring_chronological

        S = self.kv.max_slots
        T = self.token_budget
        N = self.ticks_per_dispatch
        K = self.draft_k + 1
        NG = self.draft_ngram
        Wr = self.draft_ring
        Vb = self._penalty_bins
        use_hist = batcher.needs_history(self.sampling)
        spec_sampling = self.spec_sampling
        lora = self.adapters is not None
        moe = self.num_experts > 0
        n_pools = len(self.kv._pools())
        n_ad = len(self.adapters.array_names) if lora else 0
        E = self.num_experts

        def multitick(arrays, *rest):
            rest = list(rest)
            pools0 = tuple(rest[:n_pools])
            rest = rest[n_pools:]
            ad_arrays = tuple(rest[:n_ad])
            rest = rest[n_ad:]
            (token_ids, slot_ids, positions, block_tables,
             sample_index) = rest[:5]
            rest = rest[5:]
            adapter_ids = rest.pop(0) if lora else None
            cnt0 = rest.pop(0) if use_hist else None
            rng0 = rest.pop(0)
            n_ticks = rest.pop(0)
            eos = rest.pop(0)
            remain = rest.pop(0)
            cap = rest.pop(0)
            slot_ad = rest.pop(0) if lora else None
            ring0 = rest.pop(0) if K > 1 else None
            rcnt0 = rest.pop(0) if K > 1 else None

            slot_iota = jnp.arange(S, dtype=jnp.int32)
            iota_k = jnp.arange(K, dtype=jnp.int32)[None, :]
            anchors = sample_index                       # [S]
            if K == 1:
                live0 = anchors >= 0
                dec0 = live0
                pos0 = jnp.where(
                    live0, positions[jnp.clip(anchors, 0, T - 1)], 0)
                last0 = jnp.zeros((S,), jnp.int32)
            else:
                # region layout: slot s owns flat [s*K, (s+1)*K); the
                # host packs only [last] there — decode membership,
                # last token and position read straight off the base
                # column. Prefill completions sample through the tok
                # head (anchors) and carry exactly one token.
                base_idx = slot_iota * K
                dec0 = slot_ids[base_idx] == slot_iota
                live0 = dec0 | (anchors >= 0)
                pos0 = jnp.where(dec0, positions[base_idx], 0)
                last0 = token_ids[base_idx]
                rows2d = base_idx[:, None] + iota_k      # [S, K]
            mstats0 = None
            if moe:
                mstats0 = {"counts": jnp.zeros((E,), jnp.float32),
                           "dropped": jnp.zeros((), jnp.float32),
                           "aux": jnp.zeros((), jnp.float32)}

            def cond(state):
                t, _rng, _pools, _staged, _counts, events, live = \
                    state[:7]
                return (t < n_ticks) & (
                    (t == 0)
                    | (~jnp.any(events > 0) & jnp.any(live)))

            def tick(state):
                (t, rng, pools_c, staged, counts, events, live,
                 prev_tok, cur_pos, mstats, cnt, ring, rcnt,
                 spec_prop, spec_acc, spec_hist) = state
                first = t == 0
                live_dec = live & dec0
                if K == 1:
                    # scatter rebuild at the pack-time anchors; dead
                    # slots aim at T and are dropped
                    sa = jnp.where(live, anchors, T).astype(jnp.int32)
                    tid = jnp.where(
                        first, token_ids,
                        jnp.zeros((T,), jnp.int32)
                        .at[sa].set(prev_tok, mode="drop"))
                    sid = jnp.where(
                        first, slot_ids,
                        jnp.full((T,), -1, jnp.int32)
                        .at[sa].set(slot_iota, mode="drop"))
                    pid = jnp.where(
                        first, positions,
                        jnp.zeros((T,), jnp.int32)
                        .at[sa].set(cur_pos, mode="drop"))
                    si = jnp.where(first, sample_index,
                                   jnp.where(live, anchors, -1))
                    aid = None
                    if lora:
                        aid = jnp.where(
                            first, adapter_ids,
                            jnp.zeros((T,), jnp.int32)
                            .at[sa].set(slot_ad, mode="drop"))
                    fed = None
                    k_eff = None
                else:
                    # ---- on-device draft: widen each live decode to
                    # a verify group [last, d_1..d_{K-1}] proposed by
                    # the traced n-gram scan over the token ring.
                    # EVERY tick rebuilds the region (tick 0 included:
                    # the host packed only the base column), while
                    # tick 0 keeps the packed prefill chunks past it.
                    view = ring_chronological(ring, rcnt)
                    drafts = ngram_propose_device(view, rcnt, K - 1,
                                                  max_ngram=NG)
                    fed = jnp.concatenate(
                        [prev_tok[:, None], drafts], axis=1)  # [S, K]
                    rows = jnp.where(live_dec[:, None], rows2d, T)
                    tid = jnp.where(first, token_ids,
                                    jnp.zeros((T,), jnp.int32))
                    tid = tid.at[rows].set(fed, mode="drop")
                    sid = jnp.where(first, slot_ids,
                                    jnp.full((T,), -1, jnp.int32))
                    sid = sid.at[rows].set(
                        jnp.broadcast_to(slot_iota[:, None], (S, K)),
                        mode="drop")
                    pid = jnp.where(first, positions,
                                    jnp.zeros((T,), jnp.int32))
                    pid = pid.at[rows].set(
                        cur_pos[:, None] + iota_k, mode="drop")
                    si = jnp.where(first, sample_index,
                                   jnp.full((S,), -1, jnp.int32))
                    aid = None
                    if lora:
                        aid = jnp.where(first, adapter_ids,
                                        jnp.zeros((T,), jnp.int32))
                        aid = aid.at[rows].set(
                            jnp.broadcast_to(slot_ad[:, None],
                                             (S, K)), mode="drop")
                    # per-tick draft clamp, mirroring the host
                    # drafter's horizon/capacity shrink: never past
                    # the request's remaining budget, never past the
                    # preallocated block frontier
                    k_eff = jnp.clip(
                        jnp.minimum(jnp.minimum(K - 1,
                                                remain - counts - 1),
                                    cap - cur_pos - 1), 0, K - 1)
                rng, sub = jax.random.split(rng)
                call = [arrays] + list(pools_c) + list(ad_arrays)
                call += [tid, sid, pid, block_tables, si]
                if lora:
                    call.append(aid)
                if use_hist:
                    call.append(cnt)
                call.append(sub)
                res = base_step(*call)
                out0 = res[0]
                new_pools = res[1:]
                if moe:
                    mstats = jax.tree.map(jnp.add, mstats,
                                          new_pools[-1])
                    new_pools = new_pools[:-1]
                if K == 1:
                    tok = out0
                    emitted = tok[:, None]               # [S, 1]
                    e = jnp.where(live, 1, 0)
                    m = jnp.zeros((S,), jnp.int32)
                else:
                    if spec_sampling:
                        tok, tok_v, tok_res, acc = out0
                        flags = acc[:, :K - 1] & (
                            iota_k[:, :K - 1] < k_eff[:, None])
                        m = jnp.sum(jnp.cumprod(
                            flags.astype(jnp.int32), axis=1), axis=1)
                        # accepted drafts re-emit the fed tokens, then
                        # the bonus sample (all k_eff accepted) or the
                        # residual resample at the rejection
                        fin = jnp.where(
                            (m == k_eff)[:, None],
                            jnp.take_along_axis(tok_v, m[:, None], 1),
                            jnp.take_along_axis(tok_res, m[:, None],
                                                1))[:, 0]
                        emitted = jnp.concatenate(
                            [fed[:, 1:], jnp.zeros((S, 1), jnp.int32)],
                            axis=1)
                        emitted = jnp.where(iota_k == m[:, None],
                                            fin[:, None], emitted)
                    else:
                        tok, tok_v = out0
                        eq = (fed[:, 1:] == tok_v[:, :K - 1]) & (
                            iota_k[:, :K - 1] < k_eff[:, None])
                        m = jnp.sum(jnp.cumprod(
                            eq.astype(jnp.int32), axis=1), axis=1)
                        emitted = tok_v
                    e = m + 1
                    # prefill completions emit their single sampled
                    # token through the tok head, like a 1-wide group
                    is_anch = live & ~dec0
                    e = jnp.where(is_anch, 1,
                                  jnp.where(live, e, 0))
                    emitted = jnp.where(
                        is_anch[:, None],
                        jnp.where(iota_k == 0, tok[:, None], -1),
                        emitted)
                # EOS cut: the FIRST matching token inside the
                # emitted prefix truncates it and finishes the slot —
                # the host emit() replay lands on the same token
                val = iota_k < e[:, None]
                hit = val & (eos[:, None] >= 0) & (
                    emitted == eos[:, None])
                any_hit = jnp.any(hit, axis=1)
                e = jnp.where(any_hit,
                              jnp.argmax(hit, axis=1).astype(
                                  jnp.int32) + 1, e)
                if K == 1:
                    staged = staged.at[:, t].set(
                        jnp.where(live, emitted[:, 0], -1))
                else:
                    cols = jnp.where(
                        live[:, None] & (iota_k < e[:, None]),
                        counts[:, None] + iota_k, N * K)
                    staged = staged.at[
                        slot_iota[:, None], cols].set(
                        emitted, mode="drop")
                counts = counts + jnp.where(live, e, 0)
                finish = live & (any_hit | (counts >= remain))
                nxt = cur_pos + jnp.where(live_dec, e, 0)
                overflow = live & ~finish & (nxt >= cap)
                events = (events
                          | jnp.where(finish, 1, 0)
                          | jnp.where(overflow, 2, 0))
                if use_hist:
                    # fold the emitted tokens into the count
                    # histogram so the NEXT tick's penalties see them
                    # (exactly the host's per-step history rebuild)
                    if K == 1:
                        brow = jnp.where(live, slot_iota, S)
                        cnt = cnt.at[brow, emitted[:, 0] % Vb].add(
                            1.0, mode="drop")
                    else:
                        bcol = jnp.where(
                            live[:, None] & (iota_k < e[:, None]),
                            emitted % Vb, Vb)
                        cnt = cnt.at[slot_iota[:, None], bcol].add(
                            1.0, mode="drop")
                if K == 1:
                    prev_tok = emitted[:, 0]
                else:
                    prev_tok = jnp.where(
                        live_dec,
                        jnp.take_along_axis(
                            emitted,
                            jnp.maximum(e - 1, 0)[:, None],
                            axis=1)[:, 0],
                        prev_tok)
                    ridx = jnp.where(
                        live_dec[:, None] & (iota_k < e[:, None]),
                        (rcnt[:, None] + iota_k) % Wr, Wr)
                    ring = ring.at[slot_iota[:, None], ridx].set(
                        emitted, mode="drop")
                    rcnt = rcnt + jnp.where(live_dec, e, 0)
                    ld = live_dec.astype(jnp.int32)
                    spec_prop = spec_prop + jnp.sum(k_eff * ld)
                    spec_acc = spec_acc + jnp.sum(m * ld)
                    spec_hist = spec_hist + jnp.sum(
                        jax.nn.one_hot(jnp.clip(m, 0, K - 1), K,
                                       dtype=jnp.int32)
                        * ld[:, None], axis=0)
                live = live & ~finish & ~overflow
                return (t + 1, rng, tuple(new_pools), staged, counts,
                        events, live, prev_tok, nxt, mstats, cnt,
                        ring, rcnt, spec_prop, spec_acc, spec_hist)

            zi = jnp.zeros((), jnp.int32)
            state = (zi, rng0, pools0,
                     jnp.full((S, N * K), -1, jnp.int32),
                     jnp.zeros((S,), jnp.int32),
                     jnp.zeros((S,), jnp.int32), live0,
                     last0, pos0, mstats0, cnt0, ring0, rcnt0,
                     zi, zi,
                     jnp.zeros((K,), jnp.int32) if K > 1 else zi)
            state = jax.lax.while_loop(cond, tick, state)
            (t, rng, pools_f, staged, counts, events, _live, _tok,
             _pos, mstats, _cnt, _ring, _rcnt, spec_prop, spec_acc,
             spec_hist) = state
            ctrl = (staged, counts, events, t, rng)
            if K > 1:
                ctrl += (spec_prop, spec_acc, spec_hist)
            out = (ctrl,) + tuple(pools_f)
            if moe:
                out += (mstats,)
            return out

        return multitick

    # ------------------------------------------------------------ intake
    def register_adapter(self, adapter_id, weights):
        """Register a LoRA finetune's host weights (see
        `serving.adapters.AdapterCache.register`); device slots are
        claimed lazily at admission."""
        if self.adapters is None:
            raise ValueError(
                "this engine was built without adapter support "
                "(ServingEngine(max_adapters=...))")
        return self.adapters.register(adapter_id, weights)

    def submit(self, prompt_ids, max_new_tokens=32, deadline=None,
               tenant="default", adapter_id=None, trace_id=None):
        """Queue one request. Returns the scheduler's Request handle
        (read `.output` / `.state` as the engine advances).
        `adapter_id` selects a registered LoRA adapter (None = base
        model, token-identical to an adapter-free engine)."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        maxpos = self.model.max_position_embeddings
        if len(prompt) + max_new_tokens > maxpos:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_position_embeddings "
                f"({maxpos})")
        if adapter_id is not None:
            if self.adapters is None:
                raise ValueError(
                    "request names an adapter but this engine was "
                    "built without adapter support (max_adapters=0)")
            if not self.adapters.known(adapter_id):
                raise ValueError(
                    f"adapter {adapter_id!r} is not registered on "
                    "this engine (register_adapter first)")
        req = self.scheduler.submit(prompt, max_new_tokens,
                                    eos_token_id=self.eos_token_id,
                                    deadline=deadline, tenant=tenant,
                                    adapter_id=adapter_id,
                                    trace_id=trace_id)
        if _pmetrics._enabled:
            smetrics.SERVING_QUEUE_DEPTH.set(len(self.scheduler.queue))
        return req

    def cancel(self, req):
        """Abort a request (frontend cancellation). Blocks and prefix
        locks are reclaimed immediately."""
        ok = self.scheduler.cancel(req)
        if ok and _pmetrics._enabled:
            smetrics.SERVING_REQUESTS.labels("cancelled").inc()
        return ok

    # -------------------------------------------- migration (disagg)
    def _slot_chunk(self, req, first_block, last_block):
        """Export `req`'s table blocks [first_block, last_block) as one
        transport chunk (None when the range is empty)."""
        row = self.kv.slot_blocks(req.slot)
        ids = row[first_block:last_block]
        if not ids:
            return None
        from .distributed.transport import BlockChunk
        return BlockChunk(start=int(first_block), count=len(ids),
                          arrays=self.kv.export_blocks(ids))

    def export_unshipped(self, req):
        """Stream-ahead export for a prefill in flight: the FULL blocks
        written since the last call (a full block's contents are final
        — later chunks write later blocks, and decode writes land past
        the prompt), so the decode side holds most of the KV before
        the handoff ticket even exists. Returns a BlockChunk or None."""
        if req.slot < 0:
            return None
        full = int(self.kv.slot_lens[req.slot]) // self.block_size
        chunk = self._slot_chunk(req, req.shipped_blocks, full)
        if chunk is not None:
            req.shipped_blocks = full
        return chunk

    def extract_request(self, req):
        """Pull a resident request out of this engine for migration:
        export the blocks not yet streamed ahead (all of them for a
        decode shed), capture the host state, then free the slot.
        Returns the `MigrationTicket` the destination's
        `submit_migrated` consumes. Greedy parity contract: the ticket
        carries bit-exact KV (scales included) and the full token
        history, so the destination continues the stream exactly as
        this engine would have (docs/SERVING.md)."""
        if req.slot < 0 or req.state not in ("decode", "handoff"):
            raise ValueError(
                f"request {req.req_id} not extractable "
                f"(state={req.state!r}, slot={req.slot})")
        from .distributed.transport import MigrationTicket
        slot_len = int(self.kv.slot_lens[req.slot])
        total = self.kv.blocks_for(slot_len)
        chunks = []
        tail = self._slot_chunk(req, req.shipped_blocks, total)
        if tail is not None:
            chunks.append(tail)
        ticket = MigrationTicket(
            prompt=list(req.prompt), output=list(req.output),
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.eos_token_id, deadline=req.deadline,
            tenant=req.tenant, slot_len=slot_len, total_blocks=total,
            kv_meta=self.kv.kv_meta(), chunks=chunks,
            submit_time=req.submit_time,
            first_token_time=req.first_token_time,
            cache_hit_tokens=req.cache_hit_tokens,
            preemptions=req.preemptions, created_at=self.clock(),
            adapter_id=req.adapter_id, trace_id=req.trace_id)
        if _tracing._enabled:
            _tracing.on_extracted(req, ticket, self.name)
        self.scheduler.extract(req)
        if _pmetrics._enabled:
            smetrics.SERVING_REQUESTS.labels("migrated").inc()
        return ticket

    def submit_migrated(self, ticket):
        """Admit a migrated request: validates the transported pool
        geometry against this engine's, then queues the ticket — the
        scheduler imports its blocks into a slot at the next plan (so
        the mixed step's shapes, and its one-compile contract, are
        untouched by the admission). Returns the Request handle."""
        mine = self.kv.kv_meta()
        theirs = dict(ticket.kv_meta or {})
        if theirs != mine:
            raise ValueError(
                f"migrated KV geometry {theirs} does not match this "
                f"engine's {mine} — disaggregated replicas must share "
                "block_size/kv_dtype/layer geometry")
        covered = sum(c.count for c in ticket.chunks)
        if covered != ticket.total_blocks:
            raise ValueError(
                f"ticket carries {covered} blocks but declares "
                f"{ticket.total_blocks} — transport lost a chunk")
        aid = getattr(ticket, "adapter_id", None)
        if aid is not None and (self.adapters is None
                                or not self.adapters.known(aid)):
            raise ValueError(
                f"migrated request needs adapter {aid!r}, which is "
                "not registered on this engine — register every "
                "adapter on every replica of a migrating fleet "
                "(ReplicaRouter.register_adapter does)")
        req = self.scheduler.submit_migrated(ticket)
        if _pmetrics._enabled:
            smetrics.SERVING_QUEUE_DEPTH.set(len(self.scheduler.queue))
        return req

    def _adapter_token_ids(self, sp):
        """Per-token adapter SLOT ids for one packed step, riding the
        flat token axis exactly like the sampling params do: each
        token inherits its owning slot's pinned adapter slot; padding
        (and base-model) tokens carry the null slot 0. Rebuilt
        host-side per step, so compiled shapes never depend on which
        adapters are resident."""
        slot_ad = np.zeros(self.kv.max_slots, np.int32)
        for s, req in enumerate(self.scheduler.slots):
            if req is not None:
                slot_ad[s] = req.adapter_slot
        return np.where(sp.slot_ids >= 0,
                        slot_ad[np.clip(sp.slot_ids, 0, None)],
                        0).astype(np.int32)

    def _penalty_counts(self):
        """Fixed `[max_slots, penalty_vocab_bins]` float32 token-count
        histogram for the in-step logit processors: each resident
        slot's last W (prompt + generated) tokens bucketed by
        `token % bins` — the device-updatable form of the old per-step
        history window (ISSUE 19). Rebuilt host-side per dispatch so
        the compiled shapes never depend on generation progress; the
        multi-tick loop then scatter-adds each accepted token in-loop
        so later ticks penalize earlier ticks' output without a host
        round-trip."""
        W = int(self.sampling.penalty_window)
        Vb = self._penalty_bins
        cnt = np.zeros((self.kv.max_slots, Vb), np.float32)
        for slot, req in enumerate(self.scheduler.slots):
            if req is None:
                continue
            toks = req.runtime_prompt[-W:]
            if toks:
                np.add.at(cnt[slot],
                          np.asarray(toks, np.int64) % Vb, 1.0)
        return cnt

    def _draft_ring_state(self):
        """Per-slot device token ring feeding the in-loop n-gram
        drafter: `ring [max_slots, draft_ring]` int32 with token t of
        each resident sequence at column t % draft_ring, plus
        `rcnt [max_slots]` total sequence lengths
        (`serving.draft.ring_chronological` layout). Reseeded host-side
        per dispatch — cheap, it is one window copy per resident slot —
        and advanced ON DEVICE inside the dispatch as ticks emit."""
        Wr = self.draft_ring
        S = self.kv.max_slots
        ring = np.zeros((S, Wr), np.int32)
        rcnt = np.zeros(S, np.int32)
        for slot, req in enumerate(self.scheduler.slots):
            if req is None:
                continue
            toks = req.runtime_prompt
            L = len(toks)
            w = min(L, Wr)
            if w:
                ring[slot, np.arange(L - w, L) % Wr] = toks[-w:]
            rcnt[slot] = L
        return ring, rcnt

    def sparse_skip_ratio(self):
        """Fraction of candidate KV blocks the sparse decode path
        SKIPPED (0.0 = dense, or sparsity off) — the long-context
        smoke's measured-sparsity contract."""
        if not self.sparse_candidate_blocks:
            return 0.0
        return 1.0 - (self.sparse_selected_blocks
                      / self.sparse_candidate_blocks)

    def moe_utilization_entropy(self):
        """Normalized entropy of the cumulative per-expert token
        distribution (1.0 = balanced; 0.0 = degenerate/no MoE)."""
        return _pmetrics.moe_utilization_entropy(self.moe_expert_counts)

    def _note_moe_stats(self, moe_stats):
        """Fold one step's device-side routing stats into the host
        mirrors + metrics (per-expert token counters, dropped-token
        counter, aux-loss gauge, utilization-entropy gauge)."""
        st = {k: np.asarray(v) for k, v in moe_stats.items()}
        counts = st["counts"].astype(np.float64)
        dropped = float(st["dropped"])
        self.moe_expert_counts += counts
        self.moe_dropped_total += dropped
        self.moe_last_aux = float(st["aux"])
        if _pmetrics._enabled:
            _pmetrics.record_moe_stats(
                "serving", counts, dropped, self.moe_last_aux,
                utilization=self.moe_utilization_entropy())

    # -------------------------------------------------------------- run
    def step(self):
        """One engine iteration. Returns True when any work (tokens or
        expiries) happened, False when the engine is idle/starved."""
        import jax
        import jax.numpy as jnp
        sch = self.scheduler
        # tracing state is sampled ONCE per step: recording stays
        # consistent across the step even if a monitor attaches midway
        trace_on = _tracing._enabled
        t0 = self.clock() if trace_on else None
        plan = sch.plan()
        if _pmetrics._enabled and plan.expired:
            for _ in plan.expired:
                smetrics.SERVING_REQUESTS.labels("expired").inc()
        if plan.empty:
            self._flush_deferred()
            return bool(plan.expired)
        if self._multitick:
            return self._step_multitick(plan, trace_on, t0)
        sp = pack_step(self.token_budget, self.kv.max_slots,
                       plan.decode, plan.prefills,
                       verify_width=self.draft_k + 1,
                       reserve_region=self._sparse)
        self._rng, sub = jax.random.split(self._rng)
        args = [self._arrays] + self.kv._pools()
        if self.adapters is not None:
            args += self.adapters.device_arrays()
        args += [jnp.asarray(sp.token_ids), jnp.asarray(sp.slot_ids),
                 jnp.asarray(sp.positions),
                 jnp.asarray(self.kv.block_tables),
                 jnp.asarray(sp.sample_index)]
        if self.adapters is not None:
            args.append(jnp.asarray(self._adapter_token_ids(sp)))
        if batcher.needs_history(self.sampling):
            args.append(jnp.asarray(self._penalty_counts()))
        args.append(sub)
        res = self._step_fn(*args)
        moe_stats = None
        if self.num_experts:
            res, moe_stats = res[:-1], res[-1]
        out = res[0]
        self.kv._set_pools(res[1:])
        sch.note_fed(plan)
        self.steps_run += 1
        if self._sparse and plan.decode:
            # selection arithmetic is deterministic on fixed geometry
            # (min(allocated, table width) blocks attended per decode
            # group per layer), so the skip accounting is pure host
            # math — no device readback
            for slot, tok, pos in plan.decode:
                width = 1 if np.isscalar(tok) or getattr(
                    tok, "ndim", None) == 0 else len(tok)
                n_blk = (pos + width - 1) // self.block_size + 1
                self.sparse_candidate_blocks += n_blk
                self.sparse_selected_blocks += min(
                    n_blk, self.sparse_table_width)
        tokres_np = acc_np = None
        if self.draft_k and self.spec_sampling:
            tok_np, tokv_np, tokres_np, acc_np = (np.asarray(t)
                                                  for t in out)
        elif self.draft_k:
            tok_np, tokv_np = (np.asarray(t) for t in out)
        else:
            tok_np, tokv_np = np.asarray(out), None
        now = self.clock()
        if trace_on:
            # one prefill_chunk span per planned chunk: slot residents
            # are stable between plan() and here (admissions happen
            # only inside plan), so sch.slots[slot] is the chunk's
            # request
            for slot, chunk, start, completes in plan.prefills:
                req = sch.slots[slot]
                if req is not None:
                    _tracing.TRACER.event(
                        req.trace_id, "prefill_chunk",
                        replica=self.name, ts=now, start=int(start),
                        tokens=len(chunk), completes=bool(completes))

        def emit(req, tokens, verify=False):
            """Append generated tokens; returns True when the request
            reached a terminal state (EOS / horizon)."""
            if req.state == "prefill":
                req.state = "decode"
            first = req.first_token_time is None
            gap = None
            if first:
                req.first_token_time = now
                if _pmetrics._enabled:
                    smetrics.SERVING_TTFT_SECONDS.observe(
                        now - req.submit_time)
            elif req._last_token_time is not None:
                gap = now - req._last_token_time
                if _pmetrics._enabled:
                    smetrics.SERVING_INTER_TOKEN_SECONDS.observe(gap)
            req._last_token_time = now
            if trace_on:
                # the span twins of the two histograms above: the
                # first_token event's ts minus the enqueued event's ts
                # IS `now - req.submit_time`, and decode/verify events
                # carry the same `gap` — tools/trace_smoke.py asserts
                # the sums match
                if first:
                    _tracing.on_first_token(req, self.name, ts=now)
                else:
                    _tracing.on_tokens(req, self.name, ts=now,
                                       n=len(tokens), gap=gap,
                                       verify=verify)
            for t in tokens:
                req.output.append(t)
                if len(req.output) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and t == req.eos_token_id):
                    sch.finish(req, now)
                    if _pmetrics._enabled:
                        smetrics.SERVING_REQUESTS.labels(
                            "finished").inc()
                    return True
            return False

        for slot in sp.prefill_done:
            req = sch.slots[slot]
            if req is not None:
                done = emit(req, [int(tok_np[slot])])
                if not done and self.role == "prefill":
                    # prefill-role handoff point: the first token is
                    # sampled, every prompt token's K/V is written —
                    # the request parks until the frontend extracts it
                    # toward a decode replica (a request that finished
                    # AT its first token never migrates)
                    req.state = "handoff"
                    if trace_on:
                        _tracing.TRACER.event(
                            req.trace_id, "handoff",
                            replica=self.name, ts=now)
        spec_accept = spec_groups = 0
        if self.draft_k:
            from .draft import accept_length, accept_length_sampled
            for slot, toks, pos in sp.decode_entries:
                req = sch.slots[slot]
                if req is None:
                    continue
                g = tokv_np[slot]
                if self.spec_sampling:
                    # rejection-sampling acceptance: accepted drafts
                    # re-emit the fed tokens, then the device's
                    # residual resample (rejection at m) or its bonus
                    # sample (every draft accepted)
                    m = accept_length_sampled(toks, acc_np[slot])
                    emitted = [int(t) for t in toks[1:m + 1]]
                    emitted.append(int(g[m]) if m == len(toks) - 1
                                   else int(tokres_np[slot][m]))
                else:
                    m = accept_length(toks, g)
                    emitted = [int(t) for t in g[:m + 1]]
                self.spec_proposed_total += len(toks) - 1
                self.spec_accepted_total += m
                if _pmetrics._enabled:
                    smetrics.SERVING_ACCEPT_LENGTH.observe(m + 1)
                    if len(toks) > 1:
                        smetrics.SERVING_DRAFT_TOKENS.labels(
                            "proposed").inc(len(toks) - 1)
                        smetrics.SERVING_DRAFT_TOKENS.labels(
                            "accepted").inc(m)
                if trace_on:
                    spec_accept += m + 1
                    spec_groups += 1
                done = emit(req, emitted, verify=True)
                if not done:
                    # roll back blocks whose only contents were
                    # rejected-draft K/V columns
                    freed = sch.note_accept(slot, pos + m + 1)
                    if freed and _pmetrics._enabled:
                        smetrics.SERVING_SPEC_ROLLBACKS.inc()
                        smetrics.SERVING_SPEC_ROLLBACK_BLOCKS.inc(freed)
        else:
            for slot in sp.decode_slots:
                req = sch.slots[slot]
                if req is not None:
                    emit(req, [int(tok_np[slot])])
        if moe_stats is not None:
            self._note_moe_stats(moe_stats)
        if _pmetrics._enabled:
            smetrics.SERVING_STEPS.inc()
            smetrics.SERVING_TOKENS.labels("prefill").inc(
                sp.prefill_tokens)
            smetrics.SERVING_TOKENS.labels("decode").inc(
                sp.decode_tokens)
            smetrics.SERVING_QUEUE_DEPTH.set(len(sch.queue))
            smetrics.SERVING_ACTIVE_SLOTS.set(sch.num_active)
            smetrics.SERVING_KV_BLOCKS_IN_USE.set(self.kv.blocks_in_use)
            smetrics.SERVING_KV_BLOCK_UTILIZATION.set(
                self.kv.utilization)
            smetrics.SERVING_KV_BYTES_PER_TOKEN.set(
                self.kv.kv_bytes_per_token)
            if self._sparse and self.sparse_candidate_blocks:
                skipped = (self.sparse_candidate_blocks
                           - self.sparse_selected_blocks)
                if skipped > self._sparse_skip_seen:
                    smetrics.SERVING_KV_BLOCKS_SKIPPED.inc(
                        skipped - self._sparse_skip_seen)
                    self._sparse_skip_seen = skipped
                smetrics.SERVING_SPARSE_ATTENTION_RATIO.set(
                    self.sparse_selected_blocks
                    / self.sparse_candidate_blocks)
            new_p = sch.preemption_count - self._preempt_seen
            if new_p:
                smetrics.SERVING_PREEMPTIONS.inc(new_p)
                self._preempt_seen = sch.preemption_count
            new_imp = self.kv.blocks_imported - self._imported_seen
            if new_imp:
                smetrics.SERVING_KV_BLOCKS_MIGRATED.inc(new_imp)
                self._imported_seen = self.kv.blocks_imported
            if self.prefix_cache is not None:
                pc = self.prefix_cache
                h0, m0, e0 = self._prefix_seen
                if pc.hit_tokens > h0:
                    smetrics.SERVING_PREFIX_HIT_TOKENS.inc(
                        pc.hit_tokens - h0)
                if pc.miss_tokens > m0:
                    smetrics.SERVING_PREFIX_MISS_TOKENS.inc(
                        pc.miss_tokens - m0)
                if pc.evictions > e0:
                    smetrics.SERVING_PREFIX_EVICTIONS.inc(
                        pc.evictions - e0)
                self._prefix_seen = (pc.hit_tokens, pc.miss_tokens,
                                     pc.evictions)
        if trace_on:
            # flight-recorder note: every field is a host int/float the
            # loop already holds — no device readback, no jit input.
            # The jit cache size probes a host dict; a growing value
            # across records is a compile event (the watchdog fails the
            # run outright, this just timestamps it).
            try:
                compiled = int(self._step_fn._jitted._cache_size())
            except Exception:
                compiled = -1
            self.flight.note(
                ts=t0, dur=self.clock() - t0,
                prefill_tokens=int(sp.prefill_tokens),
                decode_tokens=int(sp.decode_tokens),
                active_slots=int(sch.num_active),
                queue_depth=len(sch.queue),
                spec_accept_tokens=spec_accept,
                spec_groups=spec_groups,
                sparse_skip_ratio=(
                    1.0 - self.sparse_selected_blocks
                    / self.sparse_candidate_blocks
                    if self._sparse and self.sparse_candidate_blocks
                    else 0.0),
                blocks_imported=int(self.kv.blocks_imported),
                compile_cache_size=compiled,
                **self._flight_extra())
        return True

    # ------------------------------------- multi-tick dispatch (ISSUE 18)
    def _flush_deferred(self):
        cb, self._deferred = self._deferred, None
        if cb is not None:
            cb()

    def flush_observability(self):
        """Flush the deferred observability of the LAST multi-tick
        dispatch (its metrics/flight record normally publish after the
        NEXT dispatch launches, overlapping device execution). No-op on
        single-tick engines; the frontend calls this when going idle."""
        self._flush_deferred()

    def _auto_ticks(self, n_max):
        """ticks_per_dispatch='auto': size the next dispatch from the
        measured per-tick device time `d` and inter-dispatch host time
        `h` (EMAs) — the smallest n that keeps the amortized host share
        under ~10% of a tick, ceil(h / (0.1 d)), clamped to the staging
        width. Cold EMAs run the full budget (the measurement itself)."""
        d, h = self._tick_ema, self._gap_ema
        if not d or not h:
            return n_max
        import math
        return max(1, min(n_max, math.ceil(h / max(0.1 * d, 1e-9))))

    def _step_multitick(self, plan, trace_on, t0):
        """The multi-tick twin of `step()`'s post-plan body: preallocate
        tick capacity, launch the while_loop dispatch, harvest the
        staging buffer, and replay the emitted tokens through the same
        host bookkeeping a 1-tick engine runs per step."""
        import jax.numpy as jnp
        sch = self.scheduler
        S = self.kv.max_slots
        t_launch = self.clock()
        if self._gap_ema is not None or self._last_harvest is not None:
            gap = max(t_launch - (self._last_harvest or t_launch), 0.0)
            self._gap_ema = (gap if self._gap_ema is None
                             else 0.7 * self._gap_ema + 0.3 * gap)
        buf = self._plan_buffers[self._plan_flip]
        self._plan_flip ^= 1
        K = self.draft_k + 1
        sp = pack_step(self.token_budget, S, plan.decode,
                       plan.prefills, verify_width=K,
                       reserve_region=self._sparse, buffers=buf)
        # multi-tick only on pure-decode dispatches: a prefill chunk
        # needs the host packer next step anyway, and a prefill-role
        # engine's completions park in "handoff" — both pin n to 1
        n = self.ticks_per_dispatch if not plan.prefills else 1
        if n > 1 and self._ticks_auto:
            n = self._auto_ticks(self.ticks_per_dispatch)
        eos = np.full(S, -1, np.int32)
        remain = np.zeros(S, np.int32)
        cap = np.zeros(S, np.int32)
        for slot, _tok, pos in plan.decode:
            req = sch.slots[slot]
            if req is None:
                continue
            if req.eos_token_id is not None:
                eos[slot] = int(req.eos_token_id)
            remain[slot] = req.max_new_tokens - len(req.output)
            # FREE-block tick preallocation (scheduler.extend_for_ticks)
            # — block_tables below is snapshotted AFTER, so in-device
            # appends of later ticks land in already-mapped blocks.
            # With speculation each tick may write up to K tokens, so
            # the preallocation horizon is n * K; the in-loop draft
            # clamp (k_eff <= cap - pos - 1) keeps accepted tokens
            # inside it, and anything past it lands in the reserved
            # null block and is never read back (attention stops at
            # cap, harvest truncates to the emitted count).
            cap[slot] = (sch.extend_for_ticks(slot, pos, n * K)
                         if n * K > 1 else pos + 1)
        args = [self._arrays] + self.kv._pools()
        if self.adapters is not None:
            args += self.adapters.device_arrays()
        args += [jnp.asarray(sp.token_ids), jnp.asarray(sp.slot_ids),
                 jnp.asarray(sp.positions),
                 jnp.asarray(self.kv.block_tables),
                 jnp.asarray(sp.sample_index)]
        if self.adapters is not None:
            args.append(jnp.asarray(self._adapter_token_ids(sp)))
        if batcher.needs_history(self.sampling):
            args.append(jnp.asarray(self._penalty_counts()))
        # CHAIN key, always as a HOST array: the loop splits per tick
        # and returns the advanced chain, which harvest materializes
        # back to host — a device-resident key would flip the arg's
        # sharding between dispatch 1 and 2 and recompile the step
        args.append(np.asarray(self._rng))
        args += [jnp.asarray(np.int32(n)), jnp.asarray(eos),
                 jnp.asarray(remain), jnp.asarray(cap)]
        if self.adapters is not None:
            slot_ad = np.zeros(S, np.int32)
            for s, req in enumerate(sch.slots):
                if req is not None:
                    slot_ad[s] = req.adapter_slot
            args.append(jnp.asarray(slot_ad))
        if K > 1:
            ring, rcnt = self._draft_ring_state()
            args += [jnp.asarray(ring), jnp.asarray(rcnt)]
        res = self._step_fn(*args)
        moe_stats = None
        if self.num_experts:
            res, moe_stats = res[:-1], res[-1]
        ctrl = res[0]
        sp_prop_d = sp_acc_d = sp_hist_d = None
        if K > 1:
            (staged_d, counts_d, events_d, ticks_d, new_rng,
             sp_prop_d, sp_acc_d, sp_hist_d) = ctrl
        else:
            staged_d, counts_d, events_d, ticks_d, new_rng = ctrl
        self.kv._set_pools(res[1:])
        if self._multitick_async:
            # async device_get: start the control-output copies and
            # flush the PREVIOUS dispatch's deferred observability
            # while this dispatch still runs on device
            for a in ctrl:
                try:
                    a.copy_to_host_async()
                except Exception:
                    pass
            self._flush_deferred()
        hs0 = self.clock()
        counts_np = np.asarray(counts_d)
        events_np = np.asarray(events_d)
        staged_np = np.asarray(staged_d)
        ticks_run = int(ticks_d)
        spec_prop = spec_acc = 0
        spec_hist = None
        if K > 1:
            spec_prop = int(sp_prop_d)
            spec_acc = int(sp_acc_d)
            spec_hist = np.asarray(sp_hist_d)
            self.spec_proposed_total += spec_prop
            self.spec_accepted_total += spec_acc
        # the advanced CHAIN key comes back to host: next dispatch then
        # passes the same uncommitted-host-key signature as the first
        # (under the TP mesh a device-resident sharded key would change
        # the arg sharding and force a second compile)
        self._rng = np.asarray(new_rng)
        host_stall = self.clock() - hs0
        self._last_harvest = self.clock()
        self.host_stall_total += host_stall
        if not self._multitick_async:
            # sync mode (the bench's "before" arm): block on readback
            # first, do last dispatch's bookkeeping after — the legacy
            # ordering the async lane exists to beat
            self._flush_deferred()
        if ticks_run > 0:
            d = (self._last_harvest - t_launch) / ticks_run
            self._tick_ema = (d if self._tick_ema is None
                              else 0.7 * self._tick_ema + 0.3 * d)
            if self._gap_ema is None:
                self._gap_ema = 0.0    # arm the gap EMA from now on
        sch.note_fed(plan)
        self.steps_run += 1
        self.dispatches_run += 1
        self.device_ticks_run += ticks_run
        decode_emitted = 0
        if n > 1 or K > 1:
            # advance each decode slot to what the device actually
            # emitted and release the preallocated tail — dispatch-
            # boundary block state matches a 1-tick engine's exactly.
            # With speculation the freed tail includes blocks whose
            # only contents were rejected-draft K/V: those count as
            # spec rollbacks, same taxonomy as the 1-tick host path.
            for slot, _tok, pos in plan.decode:
                c = max(int(counts_np[slot]), 1)
                freed = sch.note_accept(slot, pos + c)
                if freed and K > 1 and _pmetrics._enabled:
                    smetrics.SERVING_SPEC_ROLLBACKS.inc()
                    smetrics.SERVING_SPEC_ROLLBACK_BLOCKS.inc(freed)
        if self._sparse and plan.decode:
            for slot, _tok, pos in plan.decode:
                c = max(int(counts_np[slot]), 1)
                for j in range(c):
                    n_blk = (pos + j) // self.block_size + 1
                    self.sparse_candidate_blocks += n_blk
                    self.sparse_selected_blocks += min(
                        n_blk, self.sparse_table_width)
        now = self.clock()
        if trace_on:
            for slot, chunk, start, completes in plan.prefills:
                req = sch.slots[slot]
                if req is not None:
                    _tracing.TRACER.event(
                        req.trace_id, "prefill_chunk",
                        replica=self.name, ts=now, start=int(start),
                        tokens=len(chunk), completes=bool(completes))

        def emit(req, tokens):
            """Same terminal bookkeeping as the 1-tick `emit`: TTFT /
            inter-token metrics, EOS + horizon replay (which lands on
            exactly the token the device's finish event flagged)."""
            if req.state == "prefill":
                req.state = "decode"
            first = req.first_token_time is None
            gap = None
            if first:
                req.first_token_time = now
                if _pmetrics._enabled:
                    smetrics.SERVING_TTFT_SECONDS.observe(
                        now - req.submit_time)
            elif req._last_token_time is not None:
                gap = now - req._last_token_time
                if _pmetrics._enabled:
                    smetrics.SERVING_INTER_TOKEN_SECONDS.observe(gap)
            req._last_token_time = now
            if trace_on:
                if first:
                    _tracing.on_first_token(req, self.name, ts=now)
                else:
                    _tracing.on_tokens(req, self.name, ts=now,
                                       n=len(tokens), gap=gap,
                                       verify=False)
            for t in tokens:
                req.output.append(t)
                if len(req.output) >= req.max_new_tokens or \
                        (req.eos_token_id is not None
                         and t == req.eos_token_id):
                    sch.finish(req, now)
                    if _pmetrics._enabled:
                        smetrics.SERVING_REQUESTS.labels(
                            "finished").inc()
                    return True
            return False

        for slot in sp.prefill_done:
            req = sch.slots[slot]
            if req is not None:
                done = emit(req, [int(staged_np[slot, 0])])
                if not done and self.role == "prefill":
                    req.state = "handoff"
                    if trace_on:
                        _tracing.TRACER.event(
                            req.trace_id, "handoff",
                            replica=self.name, ts=now)
        for slot in sp.decode_slots:
            req = sch.slots[slot]
            if req is not None:
                c = max(int(counts_np[slot]), 1)
                decode_emitted += c
                emit(req, [int(t) for t in staged_np[slot, :c]])
        ev_finish = ev_over = 0
        if n > 1:
            ev_finish = int(np.sum((events_np & 1) > 0))
            ev_over = int(np.sum((events_np & 2) > 0))
            self.early_exit_counts["finish"] += ev_finish
            self.early_exit_counts["overflow"] += ev_over
        if moe_stats is not None:
            # counts/dropped are per-tick sums; aux reports the mean
            # balance loss over the executed ticks
            moe_stats = dict(
                moe_stats,
                aux=moe_stats["aux"] / max(ticks_run, 1))
            self._note_moe_stats(moe_stats)
        # deferred observability: capture every value NOW, publish
        # after the next dispatch launches (or at idle/flush points)
        snap = dict(
            prefill_tokens=int(sp.prefill_tokens),
            decode_tokens=int(decode_emitted),
            queue_depth=len(sch.queue),
            active_slots=int(sch.num_active),
            blocks_in_use=int(self.kv.blocks_in_use),
            utilization=float(self.kv.utilization),
            bytes_per_token=float(self.kv.kv_bytes_per_token),
            new_preempt=sch.preemption_count - self._preempt_seen,
            new_imported=(self.kv.blocks_imported
                          - self._imported_seen),
            sparse_sel=self.sparse_selected_blocks,
            sparse_cand=self.sparse_candidate_blocks,
            blocks_imported=int(self.kv.blocks_imported),
            ticks=ticks_run, host_stall=float(host_stall),
            ev_finish=ev_finish, ev_over=ev_over,
            spec_prop=spec_prop, spec_acc=spec_acc,
            spec_hist=(None if spec_hist is None
                       else [int(x) for x in spec_hist]),
            dur=self.clock() - t0 if trace_on else 0.0)
        self._preempt_seen = sch.preemption_count
        self._imported_seen = self.kv.blocks_imported
        prefix_deltas = None
        if self.prefix_cache is not None:
            pc = self.prefix_cache
            h0, m0, e0 = self._prefix_seen
            prefix_deltas = (pc.hit_tokens - h0, pc.miss_tokens - m0,
                             pc.evictions - e0)
            self._prefix_seen = (pc.hit_tokens, pc.miss_tokens,
                                 pc.evictions)
        try:
            compiled = int(self._step_fn._jitted._cache_size())
        except Exception:
            compiled = -1

        def observe():
            if _pmetrics._enabled:
                smetrics.SERVING_STEPS.inc()
                smetrics.SERVING_TOKENS.labels("prefill").inc(
                    snap["prefill_tokens"])
                smetrics.SERVING_TOKENS.labels("decode").inc(
                    snap["decode_tokens"])
                smetrics.SERVING_QUEUE_DEPTH.set(snap["queue_depth"])
                smetrics.SERVING_ACTIVE_SLOTS.set(snap["active_slots"])
                smetrics.SERVING_KV_BLOCKS_IN_USE.set(
                    snap["blocks_in_use"])
                smetrics.SERVING_KV_BLOCK_UTILIZATION.set(
                    snap["utilization"])
                smetrics.SERVING_KV_BYTES_PER_TOKEN.set(
                    snap["bytes_per_token"])
                smetrics.SERVING_TICKS_PER_DISPATCH.observe(
                    snap["ticks"])
                smetrics.SERVING_HOST_STALL_SECONDS.inc(
                    snap["host_stall"])
                if snap["ev_finish"]:
                    smetrics.SERVING_EARLY_EXITS.labels("finish").inc(
                        snap["ev_finish"])
                if snap["ev_over"]:
                    smetrics.SERVING_EARLY_EXITS.labels(
                        "overflow").inc(snap["ev_over"])
                if snap["spec_prop"]:
                    smetrics.SERVING_DRAFT_TOKENS.labels(
                        "proposed").inc(snap["spec_prop"])
                    smetrics.SERVING_DRAFT_TOKENS.labels(
                        "accepted").inc(snap["spec_acc"])
                if snap["spec_hist"]:
                    # accept-length histogram bin b holds the number
                    # of verify groups that accepted exactly b drafts
                    # (device one_hot sum) — replay as m + 1 observes,
                    # the 1-tick host path's exact semantics
                    for b, cnt in enumerate(snap["spec_hist"]):
                        for _ in range(cnt):
                            smetrics.SERVING_ACCEPT_LENGTH.observe(
                                b + 1)
                if self._sparse and snap["sparse_cand"]:
                    skipped = snap["sparse_cand"] - snap["sparse_sel"]
                    if skipped > self._sparse_skip_seen:
                        smetrics.SERVING_KV_BLOCKS_SKIPPED.inc(
                            skipped - self._sparse_skip_seen)
                        self._sparse_skip_seen = skipped
                    smetrics.SERVING_SPARSE_ATTENTION_RATIO.set(
                        snap["sparse_sel"] / snap["sparse_cand"])
                if snap["new_preempt"]:
                    smetrics.SERVING_PREEMPTIONS.inc(
                        snap["new_preempt"])
                if snap["new_imported"]:
                    smetrics.SERVING_KV_BLOCKS_MIGRATED.inc(
                        snap["new_imported"])
                if prefix_deltas is not None:
                    dh, dm, de = prefix_deltas
                    if dh:
                        smetrics.SERVING_PREFIX_HIT_TOKENS.inc(dh)
                    if dm:
                        smetrics.SERVING_PREFIX_MISS_TOKENS.inc(dm)
                    if de:
                        smetrics.SERVING_PREFIX_EVICTIONS.inc(de)
            if trace_on:
                self.flight.note(
                    ts=t0, dur=snap["dur"],
                    prefill_tokens=snap["prefill_tokens"],
                    decode_tokens=snap["decode_tokens"],
                    active_slots=snap["active_slots"],
                    queue_depth=snap["queue_depth"],
                    spec_accept_tokens=(
                        snap["spec_acc"] + sum(snap["spec_hist"])
                        if snap["spec_hist"] else 0),
                    spec_groups=(sum(snap["spec_hist"])
                                 if snap["spec_hist"] else 0),
                    sparse_skip_ratio=(
                        1.0 - snap["sparse_sel"] / snap["sparse_cand"]
                        if self._sparse and snap["sparse_cand"]
                        else 0.0),
                    blocks_imported=snap["blocks_imported"],
                    compile_cache_size=compiled,
                    ticks=snap["ticks"],
                    host_stall=snap["host_stall"],
                    early_exit_finish=snap["ev_finish"],
                    early_exit_overflow=snap["ev_over"],
                    **self._flight_extra())

        if sch.has_work:
            self._deferred = observe
        else:
            # drain point: nothing will launch next, publish now
            observe()
        return True

    def run(self, max_steps=None):
        """Drive until every submitted request reaches a terminal
        state (or max_steps)."""
        steps = 0
        while self.scheduler.has_work:
            if max_steps is not None and steps >= max_steps:
                break
            if not self.step():
                raise RuntimeError(
                    "serving engine stalled: requests remain but no "
                    "step can be planned — the KV block pool "
                    f"({self.kv.allocator.capacity} blocks of "
                    f"{self.block_size}) cannot cover the resident "
                    "working set; raise num_blocks or lower max_slots")
            steps += 1
        # the last dispatch's observability may still be parked in the
        # deferred lane — publish before handing control back
        self._flush_deferred()
        return steps

    def generate_batch(self, prompts, max_new_tokens=32):
        """Submit a batch and drive to completion. Returns one list of
        generated token ids per prompt (stops at EOS inclusive)."""
        reqs = [self.submit(p, max_new_tokens) for p in prompts]
        self.run()
        return [list(r.output) for r in reqs]

    # ------------------------------------------- fleet control plane
    def example_step_args(self):
        """Zero-filled arguments matching the compiled mixed step's
        call signature exactly: an EMPTY StepPlan packs to the same
        fixed shapes every real step uses, so `fleet/export.py` can
        lower + AOT-compile the step against these without the engine
        ever serving a request (and without advancing `self._rng` —
        boot stays deterministic)."""
        import jax
        import jax.numpy as jnp
        sp = pack_step(self.token_budget, self.kv.max_slots, [], [],
                       verify_width=self.draft_k + 1,
                       reserve_region=self._sparse)
        _, sub = jax.random.split(self._rng)
        args = [self._arrays] + self.kv._pools()
        if self.adapters is not None:
            args += self.adapters.device_arrays()
        args += [jnp.asarray(sp.token_ids), jnp.asarray(sp.slot_ids),
                 jnp.asarray(sp.positions),
                 jnp.asarray(self.kv.block_tables),
                 jnp.asarray(sp.sample_index)]
        if self.adapters is not None:
            args.append(jnp.asarray(self._adapter_token_ids(sp)))
        if batcher.needs_history(self.sampling):
            args.append(jnp.asarray(self._penalty_counts()))
        args.append(sub)
        if self._multitick:
            # the while_loop wrapper's control tail (n_ticks / eos /
            # remain / cap [/ per-slot adapter ids] [/ draft ring +
            # ring counts]) — same fixed shapes every live dispatch
            # passes
            S = self.kv.max_slots
            # the loop takes the CHAIN key (as a host array, like every
            # live dispatch), not the split sub
            args[-1] = np.asarray(self._rng)
            args += [jnp.asarray(np.int32(1)),
                     jnp.asarray(np.full(S, -1, np.int32)),
                     jnp.asarray(np.zeros(S, np.int32)),
                     jnp.asarray(np.zeros(S, np.int32))]
            if self.adapters is not None:
                args.append(jnp.asarray(np.zeros(S, np.int32)))
            if self.draft_k:
                args += [jnp.asarray(
                    np.zeros((S, self.draft_ring), np.int32)),
                    jnp.asarray(np.zeros(S, np.int32))]
        return args

    def install_aot_step(self, fn):
        """Replace the instrumented mixed-step wrapper with a
        deserialized AOT executable (fleet/export.py). The replica
        then performs ZERO `serving_mixed_step` jit compiles — the
        property tools/fleet_smoke.py asserts with a budget-0
        watchdog. The flight recorder's compile-cache probe degrades
        to -1 (the AOT callable has no jit cache), which is the
        truthful reading for an executable that can never compile."""
        self._step_fn = fn

    def _prep_swap_arrays(self, arrays):
        """Host-side staging for `swap_weights`. The base engine takes
        the canonical model-order checkpoint as-is; TPServingEngine
        overrides this with the shard-major QKV permute + sharded
        placement its step layout requires."""
        return [np.asarray(a) for a in arrays]

    def _swap_jit_kwargs(self):
        """Extra jit kwargs for the swap cast (TP: out_shardings)."""
        return {}

    def swap_weights(self, arrays, version):
        """Live weight swap between steps (fleet/upgrade.py): replace
        the parameter set with a new same-architecture checkpoint
        through ONE jitted budget-1 `serving_weight_swap` cast — the
        exact compute-dtype transform `__init__` applies, so a swapped
        engine is bit-identical to one constructed from the new
        checkpoint. Same shapes/dtypes out means the mixed step's
        compiled executable keys unchanged: no recompile, one
        `serving_mixed_step` compile per engine holds across any
        number of upgrades. Must be called with the engine idle
        (drained) — in-flight requests would otherwise mix versions
        mid-sequence."""
        import jax.numpy as jnp
        if self._moe_weight_bits:
            raise ValueError(
                "live weight swap on an engine-side quantized MoE "
                "stack is unsupported: the quantization transform is "
                "not shape-preserving per tensor — export a new "
                "bundle and boot a fresh replica instead")
        if len(arrays) != len(self._arrays):
            raise ValueError(
                f"checkpoint has {len(arrays)} tensors, engine holds "
                f"{len(self._arrays)} — not the same architecture")
        prep = self._prep_swap_arrays(arrays)
        for new, old in zip(prep, self._arrays):
            if tuple(new.shape) != tuple(old.shape):
                raise ValueError(
                    f"weight shape {tuple(new.shape)} != engine "
                    f"shape {tuple(old.shape)}: live swap requires "
                    "an architecture-identical checkpoint")
        if self._swap_fn is None:
            dts = tuple(jnp.dtype(a.dtype) for a in self._arrays)

            def _load(new):
                return [a.astype(dt) for a, dt in zip(new, dts)]

            self._swap_fn = instrumented_jit(
                _load, SWAP_FN_NAME, **self._swap_jit_kwargs())
        self._arrays = list(self._swap_fn(prep))
        # cached prefix KV was computed under the OLD weights — serving
        # it to post-swap requests would silently mix versions
        if self.prefix_cache is not None:
            self.prefix_cache.evict_all()
        self.weights_version = str(version)

    def close(self, *, spill_prefix=None):
        """Release the engine's cached KV state; optionally spill the
        radix prefix cache (tree + exported block payloads) to
        `spill_prefix` first, so a future replica can warm-boot with a
        non-empty cache (`RadixPrefixCache.spill`/`restore`;
        docs/DEPLOYMENT.md). Returns the number of blocks spilled.
        Idempotent; the engine must be drained (no resident
        requests)."""
        self._flush_deferred()
        spilled = 0
        if self.prefix_cache is not None:
            if spill_prefix is not None:
                spilled = self.prefix_cache.spill(spill_prefix)
            self.prefix_cache.evict_all()
        return spilled
