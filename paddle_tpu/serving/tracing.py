"""Fleet-wide request tracing for the serving engine (ISSUE 16).

Two host-side event stores, both bounded, both branch-gated like
`profiler.metrics._enabled`:

* **Request traces** (`TRACER`, a `RequestTracer`) — one stitched
  span/event timeline per request: enqueued → admitted → prefill
  chunks → first token → handoff export → migration transport →
  decode admission → decode/verify steps → preempted / re-prefilled →
  finished | expired | cancelled. The trace id is minted at router
  dispatch (or lazily at engine submit for solo engines) and
  propagated Frontend → Scheduler → Engine → `MigrationTicket` →
  the destination replica's scheduler, so ONE trace survives disagg
  handoff, shed migration and failover. A failover re-dispatch REOPENS
  a trace the dying replica's cancel path already closed (see
  `_REOPEN_EVENTS`) — the surviving replica's terminal outcome wins.
* **Step flight recorders** (`StepFlightRecorder`, one per engine) —
  a bounded ring of per-step records (role, tokens prefilled/decoded,
  active slots, spec accept length, sparse skip ratio, blocks
  imported, jit cache size, step wall time) exportable as chrome
  "X" slices on an `engine:<name>` track.

Both stores register with the profiler's provider hooks
(`profiler.register_chrome_source` / `register_summary_section`), so
`profiler.export_chrome_tracing` and `profiler.summary()` merge them
with the existing host spans + registry counters — no profiler →
serving import, the dependency points the other way.

Hot-path discipline: every call site in engine/scheduler/router/
transport guards with ``if tracing._enabled:`` so recording off costs
one branch; recording on touches only host ints/floats already
computed by the step loop — no device readbacks, no new jit inputs,
zero extra compiles (tests/test_tracing.py's overhead contract).

Env knobs: ``PADDLE_TPU_TRACE=1`` enables at import,
``PADDLE_TPU_TRACE_CAPACITY`` bounds the retained-trace table
(default 2048, oldest finished evicted first),
``PADDLE_TPU_TRACE_EVENTS_MAX`` bounds events per trace (default 512),
``PADDLE_TPU_FLIGHT_STEPS`` bounds each flight ring (default 4096).
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time
import weakref

from ..profiler import metrics as _pmetrics
from . import metrics as _smetrics

__all__ = [
    "TRACER", "RequestTracer", "Trace", "TraceEvent",
    "StepFlightRecorder", "enable", "disable", "enabled",
    "register_flight_recorder", "flight_recorders",
]

_enabled = os.environ.get(
    "PADDLE_TPU_TRACE", "0").lower() not in ("0", "", "false")


def enable():
    """Turn request tracing on process-wide (idempotent)."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


#: events that REOPEN a finished trace. A replica death makes the dying
#: frontend's stop() cancel its live requests — the engine-side cancel
#: closes the trace "cancelled" before the router re-dispatches the
#: SAME request elsewhere. The re-dispatch (and the destination
#: enqueue) must un-close it so the surviving replica's real outcome
#: lands on the one stitched trace.
_REOPEN_EVENTS = frozenset({"dispatched", "enqueued"})

#: span taxonomy (docs/OBSERVABILITY.md documents each): the decode
#: loop coalesces one decode_step/verify_step event per emit, not one
#: per token — `tokens`/`gap` attrs carry the detail.
EVENT_NAMES = (
    "dispatched", "enqueued", "admitted", "prefill_chunk",
    "first_token", "handoff", "handoff_export", "migration_transport",
    "decode_admission", "decode_step", "verify_step", "preempted",
    "failover", "finished", "expired", "cancelled", "error",
)


class TraceEvent:
    __slots__ = ("name", "ts", "replica", "attrs")

    def __init__(self, name, ts, replica, attrs):
        self.name = name
        self.ts = ts
        self.replica = replica
        self.attrs = attrs

    def as_dict(self):
        d = {"name": self.name, "ts": self.ts}
        if self.replica is not None:
            d["replica"] = self.replica
        if self.attrs:
            d.update(self.attrs)
        return d

    def __repr__(self):
        return (f"TraceEvent({self.name!r}, ts={self.ts:.6f}, "
                f"replica={self.replica!r})")


class Trace:
    """One request's stitched timeline. Timestamps are clamped monotone
    per trace at record time (fleet clocks are per-engine monotonic
    clocks in one process; the clamp absorbs sub-microsecond races
    between the router thread and engine executor threads)."""

    __slots__ = ("trace_id", "tenant", "events", "done", "outcome",
                 "dropped_events", "_last_ts")

    def __init__(self, trace_id, tenant):
        self.trace_id = trace_id
        self.tenant = tenant
        self.events = []
        self.done = False
        self.outcome = None
        self.dropped_events = 0
        self._last_ts = None

    @property
    def replicas(self):
        return sorted({e.replica for e in self.events
                       if e.replica is not None})

    def first(self, name):
        for e in self.events:
            if e.name == name:
                return e
        return None

    def monotone(self):
        ts = [e.ts for e in self.events]
        return all(a <= b for a, b in zip(ts, ts[1:]))

    def derive(self):
        """Span-derived latencies — defined so they MATCH the registry
        histograms exactly: enqueued.ts is `req.submit_time` and
        first_token.ts the engine's emit-time `now`, the same two
        numbers `SERVING_TTFT_SECONDS` subtracts."""
        enq = self.first("enqueued")
        adm = self.first("admitted")
        ft = self.first("first_token")
        gaps = [e.attrs.get("gap") for e in self.events
                if e.name in ("decode_step", "verify_step")
                and e.attrs.get("gap") is not None]
        d = {
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "outcome": self.outcome,
            "replicas": self.replicas,
            "events": len(self.events),
            "ttft": (ft.ts - enq.ts) if ft and enq else None,
            "queue_wait": (adm.ts - enq.ts) if adm and enq else None,
            "inter_token": gaps,
        }
        exp = self.first("handoff_export")
        if exp is not None:
            # handoff gap: export on the source to the next token the
            # destination emitted (the stream stall a migration costs)
            for e in self.events:
                if e.ts >= exp.ts and e.name in (
                        "first_token", "decode_step", "verify_step"):
                    d["handoff_gap"] = e.ts - exp.ts
                    break
        return d

    def as_dict(self):
        return {"trace_id": self.trace_id, "tenant": self.tenant,
                "outcome": self.outcome, "done": self.done,
                "dropped_events": self.dropped_events,
                "events": [e.as_dict() for e in self.events]}


class RequestTracer:
    """Process-global trace table + observer fan-out.

    Thread-safe: the router event loop, every engine's executor thread
    and the scheduler all record under one lock (host dict/list ops —
    nanoseconds against a multi-ms step). Observers (the SLO plane)
    are notified OUTSIDE the lock; observer exceptions are swallowed —
    observability must never take down the serving loop."""

    def __init__(self, capacity=None, max_events=None,
                 clock=time.monotonic):
        if capacity is None:
            capacity = int(os.environ.get(
                "PADDLE_TPU_TRACE_CAPACITY", 2048))
        if max_events is None:
            max_events = int(os.environ.get(
                "PADDLE_TPU_TRACE_EVENTS_MAX", 512))
        self.capacity = max(1, int(capacity))
        self.max_events = max(8, int(max_events))
        self.clock = clock
        self._traces = collections.OrderedDict()
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._observers = []
        self._open = 0   # incremental: scanning the table per event
        self.dropped_traces = 0   # would be O(capacity) on the hot path

    # ------------------------------------------------------ lifecycle
    def mint(self, tenant="default"):
        """New trace id (router dispatch / solo engine submit)."""
        tid = f"tr-{next(self._seq):08x}"
        with self._lock:
            self._traces[tid] = Trace(tid, str(tenant))
            self._open += 1
            self._evict_locked()
        self._set_active_gauge()
        return tid

    def _evict_locked(self):
        while len(self._traces) > self.capacity:
            # drop the oldest FINISHED trace first; if every retained
            # trace is still open, drop the oldest outright (a stuck
            # fleet must not pin unbounded memory)
            victim = None
            for k, tr in self._traces.items():
                if tr.done:
                    victim = k
                    break
            if victim is None:
                victim = next(iter(self._traces))
            if not self._traces[victim].done:
                self._open -= 1
            del self._traces[victim]
            self.dropped_traces += 1

    def event(self, trace_id, name, replica=None, ts=None, **attrs):
        """Record one span event. Unknown ids get a shell trace (late
        enable / post-eviction stitching stays lossy-but-safe); events
        after a terminal are dropped unless `name` reopens the trace."""
        if not _enabled or trace_id is None:
            return
        if ts is None:
            ts = self.clock()
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                tr = Trace(trace_id, str(attrs.get("tenant", "default")))
                self._traces[trace_id] = tr
                self._open += 1
                self._evict_locked()
            if tr.done:
                if name in _REOPEN_EVENTS:
                    tr.done = False
                    tr.outcome = None
                    self._open += 1
                else:
                    return
            if len(tr.events) >= self.max_events:
                tr.dropped_events += 1
                if _pmetrics._enabled:
                    _smetrics.SERVING_TRACE_EVENTS_DROPPED.inc()
                return
            if tr._last_ts is not None and ts < tr._last_ts:
                ts = tr._last_ts
            tr._last_ts = ts
            tr.events.append(TraceEvent(name, ts, replica, attrs))
        if _pmetrics._enabled:
            _smetrics.SERVING_TRACE_EVENTS.labels(name).inc()
        self._set_active_gauge()

    def finish(self, trace_id, outcome, replica=None, ts=None, **attrs):
        """Close a trace with a terminal outcome. Idempotent: the first
        terminal wins (the router's abandon path and the engine's
        cancel path may both fire; double-closing would double-count
        `SERVING_TRACES`)."""
        if not _enabled or trace_id is None:
            return
        if ts is None:
            ts = self.clock()
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None or tr.done:
                return
            if tr._last_ts is not None and ts < tr._last_ts:
                ts = tr._last_ts
            tr._last_ts = ts
            # the terminal event always lands, even past max_events
            tr.events.append(TraceEvent(outcome, ts, replica, attrs))
            tr.done = True
            tr.outcome = outcome
            self._open -= 1
        if _pmetrics._enabled:
            _smetrics.SERVING_TRACES.labels(outcome).inc()
        self._set_active_gauge()

    def _set_active_gauge(self):
        if _pmetrics._enabled:
            _smetrics.SERVING_TRACE_ACTIVE.set(self._open)

    # ------------------------------------------------------- queries
    def get(self, trace_id):
        with self._lock:
            return self._traces.get(trace_id)

    def traces(self):
        with self._lock:
            return list(self._traces.values())

    def active(self):
        """Open traces — the smoke tool's orphan check: after a clean
        drain this must be empty."""
        with self._lock:
            return [t for t in self._traces.values() if not t.done]

    def reset(self):
        with self._lock:
            self._traces.clear()
            self._open = 0
            self.dropped_traces = 0
        self._set_active_gauge()

    # ------------------------------------------------------ observers
    def add_observer(self, obs):
        if obs not in self._observers:
            self._observers.append(obs)

    def remove_observer(self, obs):
        try:
            self._observers.remove(obs)
        except ValueError:
            pass

    def _notify(self, method, *args):
        for obs in list(self._observers):
            fn = getattr(obs, method, None)
            if fn is None:
                continue
            try:
                fn(*args)
            except Exception:
                pass

    # ----------------------------------------------- chrome / summary
    def chrome_events(self):
        """Per-trace track: phase "X" slices (queued / prefill /
        decode) + one instant per raw event, ts in µs like the host
        recorder."""
        pid = os.getpid()
        out = []
        for tr in self.traces():
            tid = f"trace:{tr.trace_id}"
            for e in tr.events:
                out.append({"name": e.name, "ph": "i", "s": "t",
                            "ts": e.ts * 1e6, "pid": pid, "tid": tid,
                            "args": e.as_dict()})
            d = tr.derive()
            enq = tr.first("enqueued")
            adm = tr.first("admitted")
            ft = tr.first("first_token")
            last = tr.events[-1] if tr.events else None
            for name, a, b in (("queued", enq, adm),
                               ("prefill", adm, ft),
                               ("decode", ft, last)):
                if a is not None and b is not None and b.ts >= a.ts:
                    out.append({"name": f"{name}[{tr.tenant}]",
                                "ph": "X", "ts": a.ts * 1e6,
                                "dur": (b.ts - a.ts) * 1e6,
                                "pid": pid, "tid": tid,
                                "args": {"trace_id": tr.trace_id}})
        return out

    def summary_table(self):
        traces = self.traces()
        if not traces:
            return ""
        by_outcome = collections.Counter(
            t.outcome or "open" for t in traces)
        ttfts = [d["ttft"] for d in (t.derive() for t in traces)
                 if d["ttft"] is not None]
        lines = ["---- request traces (serving.tracing) ----",
                 f"{'Outcome':16s} {'Traces':>8s}"]
        for outcome, n in sorted(by_outcome.items()):
            lines.append(f"{outcome:16s} {n:>8d}")
        if ttfts:
            lines.append(f"span-derived TTFT mean "
                         f"{sum(ttfts) / len(ttfts) * 1e3:.2f} ms over "
                         f"{len(ttfts)} trace(s)")
        if self.dropped_traces:
            lines.append(f"(trace table evicted {self.dropped_traces}; "
                         f"raise PADDLE_TPU_TRACE_CAPACITY)")
        return "\n".join(lines)


TRACER = RequestTracer()


# ---------------------------------------------------------------- hooks
# Engine/scheduler/router/transport call these; every CALL SITE guards
# with `if tracing._enabled:` so the off path stays one branch — the
# re-check inside is defense for direct callers, not the contract.

def ensure_trace(req):
    """Attach a trace id to a request, minting one when the router did
    not (solo engines submit without a frontend)."""
    if req.trace_id is None:
        req.trace_id = TRACER.mint(tenant=req.tenant)
    return req.trace_id


def on_submit(req, replica=None):
    ensure_trace(req)
    TRACER.event(req.trace_id, "enqueued", replica=replica,
                 ts=req.submit_time, tenant=req.tenant,
                 prompt_tokens=len(req.prompt))


def on_submit_migrated(req, replica=None, ts=None):
    ensure_trace(req)
    TRACER.event(req.trace_id, "decode_admission", replica=replica,
                 ts=ts, tenant=req.tenant, tokens_done=len(req.output))


def on_admitted(req, replica=None, kind="prefill", ts=None):
    """kind: "prefill" (fresh), "re_prefill" (after preemption, or a
    migrant that lost its imported blocks), "import" (migrated-in KV).
    Only the fresh admission observes the queue-wait histogram — its
    span twin is `admitted.ts - enqueued.ts` of the same trace."""
    TRACER.event(req.trace_id, "admitted", replica=replica, ts=ts,
                 kind=kind, slot=req.slot,
                 cached_tokens=req.cache_hit_tokens)
    if (kind == "prefill" and _pmetrics._enabled and ts is not None):
        _smetrics.SERVING_TRACE_QUEUE_WAIT.observe(
            max(0.0, ts - req.submit_time))


def on_first_token(req, replica=None, ts=None):
    TRACER.event(req.trace_id, "first_token", replica=replica, ts=ts)
    if ts is not None:
        TRACER._notify("on_ttft", req.tenant, ts - req.submit_time, ts)


def on_tokens(req, replica=None, ts=None, n=1, gap=None, verify=False):
    TRACER.event(req.trace_id,
                 "verify_step" if verify else "decode_step",
                 replica=replica, ts=ts, tokens=n, gap=gap)
    if gap is not None:
        TRACER._notify("on_inter_token", req.tenant, gap, ts)


def on_preempted(req, replica=None, ts=None):
    TRACER.event(req.trace_id, "preempted", replica=replica, ts=ts,
                 preemptions=req.preemptions)


def on_extracted(req, ticket, replica=None):
    TRACER.event(req.trace_id, "handoff_export", replica=replica,
                 ts=ticket.created_at, slot_len=ticket.slot_len,
                 blocks=sum(c.count for c in ticket.chunks),
                 shipped_ahead=ticket.total_blocks
                 - sum(c.count for c in ticket.chunks))


def on_transport(trace_id, src, dst, nbytes=0, blocks=0):
    TRACER.event(trace_id, "migration_transport",
                 replica=f"{src}->{dst}", bytes=nbytes, blocks=blocks)


def on_terminal(req, outcome, replica=None, ts=None):
    missed = outcome == "expired" or (
        req.deadline is not None and ts is not None
        and ts > req.deadline)
    TRACER.finish(req.trace_id, outcome, replica=replica, ts=ts,
                  tokens=len(req.output), deadline_missed=missed)
    TRACER._notify("on_outcome", req.tenant, outcome, missed,
                   ts if ts is not None else TRACER.clock())


# ------------------------------------------------- step flight recorder
_FLIGHT = weakref.WeakSet()


def register_flight_recorder(rec):
    _FLIGHT.add(rec)


def flight_recorders():
    return list(_FLIGHT)


class StepFlightRecorder:
    """Bounded per-engine ring of per-step records (ISSUE 16 tentpole
    (b)). The engine notes one record per `step()` — host ints/floats
    it already holds — only when tracing is enabled; the ring is sized
    by PADDLE_TPU_FLIGHT_STEPS (default 4096) so a long-lived replica
    keeps a recent flight window, not unbounded history."""

    def __init__(self, engine_name, role, maxlen=None):
        if maxlen is None:
            maxlen = int(os.environ.get(
                "PADDLE_TPU_FLIGHT_STEPS", 4096))
        self.engine_name = engine_name
        self.role = role
        self.maxlen = max(1, int(maxlen))
        self.records = collections.deque(maxlen=self.maxlen)
        self.dropped = 0
        self.steps = 0

    def note(self, **fields):
        if len(self.records) == self.maxlen:
            self.dropped += 1
        self.records.append(fields)
        self.steps += 1

    def chrome_events(self):
        pid = os.getpid()
        tid = f"engine:{self.engine_name}"
        out = []
        for r in self.records:
            args = {k: v for k, v in r.items()
                    if k not in ("ts", "dur")}
            out.append({"name": f"step[{self.role}]", "ph": "X",
                        "ts": r.get("ts", 0.0) * 1e6,
                        "dur": r.get("dur", 0.0) * 1e6,
                        "pid": pid, "tid": tid, "args": args})
        return out

    def summary(self):
        recs = list(self.records)
        agg = {"engine": self.engine_name, "role": self.role,
               "steps": self.steps, "dropped": self.dropped}
        if recs:
            agg["prefill_tokens"] = sum(
                r.get("prefill_tokens", 0) for r in recs)
            agg["decode_tokens"] = sum(
                r.get("decode_tokens", 0) for r in recs)
            durs = [r.get("dur", 0.0) for r in recs]
            agg["step_ms_mean"] = sum(durs) / len(durs) * 1e3
            agg["step_ms_max"] = max(durs) * 1e3
            # device-resident multi-tick dispatches (ISSUE 18): ticks
            # the while_loop ran per dispatch plus the event-bitmask
            # exit taxonomy — absent on single-tick engines, whose
            # records carry no tick fields
            ticks = [r["ticks"] for r in recs if "ticks" in r]
            if ticks:
                agg["dispatches"] = len(ticks)
                agg["ticks_total"] = sum(ticks)
                agg["ticks_per_dispatch_mean"] = (
                    sum(ticks) / len(ticks))
                agg["early_exit_finish"] = sum(
                    r.get("early_exit_finish", 0) for r in recs)
                agg["early_exit_overflow"] = sum(
                    r.get("early_exit_overflow", 0) for r in recs)
                agg["host_stall_s"] = sum(
                    r.get("host_stall", 0.0) for r in recs)
        return agg


# ----------------------------------------------- profiler registration
def _chrome_source():
    events = []
    for rec in flight_recorders():
        events.extend(rec.chrome_events())
    events.extend(TRACER.chrome_events())
    return events


def _summary_section():
    parts = []
    tbl = TRACER.summary_table()
    if tbl:
        parts.append(tbl)
    flights = [rec.summary() for rec in flight_recorders()
               if rec.steps]
    if flights:
        lines = ["---- step flight recorders (serving.tracing) ----",
                 f"{'Engine':14s} {'Role':8s} {'Steps':>7s} "
                 f"{'Prefill':>8s} {'Decode':>8s} {'ms/step':>8s}"]
        for f in sorted(flights, key=lambda f: f["engine"]):
            lines.append(
                f"{f['engine']:14s} {f['role']:8s} {f['steps']:>7d} "
                f"{f.get('prefill_tokens', 0):>8d} "
                f"{f.get('decode_tokens', 0):>8d} "
                f"{f.get('step_ms_mean', 0.0):>8.2f}")
        parts.append("\n".join(lines))
    return "\n\n".join(parts)


from .. import profiler as _profiler  # noqa: E402  (cycle-safe: the
# profiler package never imports serving; registration at import time
# is what lets export_chrome_tracing/summary() see these stores)
_profiler.register_chrome_source(_chrome_source)
_profiler.register_summary_section(_summary_section)
