"""SLO-burn-driven autoscaling (ISSUE 17 (c)).

The decision loop consumes ONLY host-side registry state — the SLO
monitor's sliding windows (PR 16), the router's queue depths, and the
flight recorders' measured step times. No device readback sits on the
decision path (the smoke runs it under `guards.sanitize`).

Discipline borrowed from `parallel.auto_tuner.tune()`: decisions are
gated by a CALIBRATED COST MODEL, not raw threshold crossings —

* `predict_ttft(extra)` — queued work per replica x measured mean
  step seconds: the admission-to-first-token latency the fleet would
  see with `extra` more (or fewer) replicas at current load;
* `predict_inter_token()` — the measured step time itself (a decode
  emits at most one token per resident slot per step, so the step
  period IS the inter-token floor);

and hysteresis keeps the fleet from flapping:

* **scale-up** only on SUSTAINED burn: some objective's burn rate
  must exceed `burn_threshold` continuously for `sustain_s`;
* **scale-down** only after `recovery_s` of every objective healthy
  AND only when the cost model predicts the post-removal TTFT still
  meets the strictest tenant target;
* a global `cooldown_s` separates consecutive decisions in either
  direction.

`SLOAutoscaler.step()` evaluates once and applies at most one
decision through the controller's boot/retire plane; `run()` loops
it. Every decision (and its model inputs) lands in `.decisions` for
the smoke's exactly-one-scale-up assertion.
"""
from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class AutoscalerPolicy:
    """Hysteresis + bounds contract (documented in
    docs/DEPLOYMENT.md; the smoke pins the semantics)."""
    min_replicas: int = 1
    max_replicas: int = 4
    burn_threshold: float = 1.0   # burn rate above this = burning
    sustain_s: float = 0.1        # burn must persist this long
    recovery_s: float = 0.3       # all-ok this long before scale-down
    cooldown_s: float = 0.5       # min gap between applied decisions


class SLOAutoscaler:
    def __init__(self, controller, monitor, *, policy=None,
                 clock=None):
        self.controller = controller
        self.monitor = monitor
        self.policy = policy or AutoscalerPolicy()
        self.clock = clock or controller.clock
        self._burn_since: Optional[float] = None
        self._ok_since: Optional[float] = None
        self._last_applied: Optional[float] = None
        #: applied decisions: dicts with ts/direction/reason/replicas/
        #: predicted_ttft — the smoke's convergence evidence
        self.decisions = []

    # ------------------------------------------------------ cost model
    def mean_step_seconds(self):
        """Measured mean mixed-step wall time across the fleet's
        flight recorders (host floats the engines already noted);
        0.0 when tracing has recorded nothing yet."""
        durs = []
        for idx in self.controller.active_replicas():
            rec = getattr(
                self.controller.router.frontends[idx].engine,
                "flight", None)
            if rec is not None:
                durs.extend(r.get("dur", 0.0) for r in rec.records)
        return sum(durs) / len(durs) if durs else 0.0

    def queued_requests(self):
        r = self.controller.router
        return sum(r.queue_depth(i)
                   for i in self.controller.active_replicas())

    def predict_ttft(self, extra_replicas=0):
        """Queue-depth x step-time TTFT estimate with
        `extra_replicas` more (negative: fewer) replicas sharing the
        same load."""
        n = len(self.controller.active_replicas()) + extra_replicas
        if n <= 0:
            return float("inf")
        return (self.queued_requests() / n) * self.mean_step_seconds()

    def predict_inter_token(self):
        return self.mean_step_seconds()

    def _strictest_ttft_target(self):
        """Tightest configured ttft_p95 target across tenants — the
        bar a scale-down's predicted TTFT must clear."""
        cfg = self.monitor.config
        vals = [cfg.default.get("ttft_p95")]
        vals += [t.get("ttft_p95") for t in cfg.tenants.values()]
        vals = [v for v in vals if v is not None]
        return min(vals) if vals else 0.5

    # ------------------------------------------------------- decisions
    def _burning(self, report):
        """(tenant, objective, burn) triples above threshold."""
        out = []
        for tenant, objs in report.items():
            for obj, d in objs.items():
                if d.get("burn_rate", 0.0) > self.policy.burn_threshold:
                    out.append((tenant, obj, d["burn_rate"]))
        return out

    def evaluate(self, now=None):
        """One decision or None — PURE policy arithmetic over the
        monitor's report + router depths (callable from tests without
        applying anything)."""
        now = self.clock() if now is None else now
        pol = self.policy
        report = self.monitor.evaluate(now)
        burning = self._burning(report)
        n = len(self.controller.active_replicas())
        cooled = (self._last_applied is None
                  or now - self._last_applied >= pol.cooldown_s)
        if burning:
            self._ok_since = None
            if self._burn_since is None:
                self._burn_since = now
            sustained = now - self._burn_since >= pol.sustain_s
            if sustained and cooled and n < pol.max_replicas:
                tenant, obj, burn = max(burning, key=lambda t: t[2])
                return {"ts": now, "direction": "up", "reason": obj,
                        "tenant": tenant, "burn": burn, "replicas": n,
                        "predicted_ttft": self.predict_ttft(+1),
                        "predicted_inter_token":
                            self.predict_inter_token()}
            return None
        self._burn_since = None
        if self._ok_since is None:
            self._ok_since = now
        recovered = now - self._ok_since >= pol.recovery_s
        if recovered and cooled and n > pol.min_replicas:
            after = self.predict_ttft(-1)
            if after <= self._strictest_ttft_target():
                return {"ts": now, "direction": "down",
                        "reason": "recovered", "replicas": n,
                        "predicted_ttft": after,
                        "predicted_inter_token":
                            self.predict_inter_token()}
        return None

    async def step(self):
        """Evaluate once; apply at most one decision. Returns the
        applied decision (or None)."""
        decision = self.evaluate()
        if decision is None:
            return None
        if decision["direction"] == "up":
            idx = await self.controller.scale_up(decision["reason"])
            decision["replica"] = idx
        else:
            idx = await self.controller.scale_down(decision["reason"])
            decision["replica"] = idx
        self._last_applied = decision["ts"]
        # both hysteresis clocks restart: the new census must re-earn
        # its next decision from scratch
        self._burn_since = None
        self._ok_since = None
        self.decisions.append(decision)
        return decision

    async def run(self, interval=0.05):
        """Background loop (cancelled by the owner, like the router's
        prober)."""
        import asyncio
        while True:
            await self.step()
            await asyncio.sleep(interval)
