"""Versioned AOT boot bundles for serving replicas (ISSUE 17 (a)).

A bundle is one directory per checkpoint version:

    <root>/<version>/
        manifest.json       model config + engine knobs + kv_meta +
                            weight manifest + executable index
        weights.npz         canonical model-order host arrays
                            (pre-compute-dtype-cast, `w00000`, ...)
        step__<role>__tp<n>.bin
                            pickled (payload, in_tree, out_tree) from
                            `jax.experimental.serialize_executable`
                            for the jitted mixed step, lowered against
                            `engine.example_step_args()`

The default root sits NEXT TO the persistent kernel-autotune cache
(`ops.pallas.autotune.user_cache_path()`): both are
build-once-boot-many artifacts of the same deployment.

Boot path: `boot_engine_from_bundle` reconstructs the model from the
manifest, injects the bundled weights into the model tensors BEFORE
engine construction (so the engine's own compute-dtype cast / MoE
quantization / TP shard layout all apply unchanged — a booted engine
is bit-identical to the exporting one), then installs the
deserialized executable via `engine.install_aot_step`. The replica
performs ZERO `serving_mixed_step` jit compiles — watchdog-assertable
with `guards.sanitize(budgets={"serving_mixed_step": 0})` — and
serves its first token straight off the deserialized executable.

On a jax without executable serialization the bundle still carries
config + weights; boot falls back to the ordinary jit path, where the
persistent HLO compilation cache (conftest wires one) absorbs most of
the compile cost. `FleetBundle.has_executable` tells the two apart.
"""
from __future__ import annotations

import dataclasses
import json
import os
import pickle

import numpy as np

MANIFEST = "manifest.json"
WEIGHTS = "weights.npz"
FORMAT = 1


def _serialize_mod():
    """The 0.4.x AOT (de)serialization entry points, or None when this
    jax build lacks them (the persistent-HLO-cache fallback)."""
    try:
        from jax.experimental import serialize_executable
        return serialize_executable
    except Exception:
        return None


def aot_available():
    return _serialize_mod() is not None


def default_bundle_root():
    """`<dir of the persistent autotune cache>/fleet_bundles`."""
    from ...ops.pallas import autotune as _kt
    return os.path.join(os.path.dirname(_kt.user_cache_path()),
                        "fleet_bundles")


def _exec_key(role, tp):
    return f"{role}-tp{int(tp)}"


def _exec_file(role, tp):
    return f"step__{role}__tp{int(tp)}.bin"


def model_config(model):
    """Recoverable GPTForGeneration constructor kwargs (+ the flags a
    faithful rebuild needs). Exotic stacks can bypass this entirely
    with `boot_engine_from_bundle(model_factory=...)`."""
    dec = model.decoder
    cfg = {
        "vocab_size": int(model.vocab_size),
        "hidden_size": int(model.hidden_size),
        "num_layers": int(dec.num_layers),
        "num_attention_heads": int(dec.num_heads),
        "intermediate_size": int(dec.dim_feedforward),
        "max_position_embeddings": int(model.max_position_embeddings),
        "compute_dtype": str(getattr(model, "_compute_dtype",
                                     "float32")),
        "weight_only": "WeightOnly" in type(dec).__name__,
    }
    n_exp = int(getattr(dec, "_num_experts", 0))
    if n_exp:
        cfg["moe"] = {"num_expert": n_exp,
                      "top_k": int(getattr(dec, "_top_k", 2))}
    return cfg


def engine_config(engine):
    """The engine-constructor knobs a replica boot must replay; the
    bundle pins them so every booted replica shares the exporting
    engine's compiled-step signature."""
    kv = engine.kv
    cfg = {
        "max_slots": int(kv.max_slots),
        "block_size": int(engine.block_size),
        "num_blocks": int(kv.num_blocks),
        "max_seq_len": int(kv.max_blocks_per_slot * kv.block_size),
        "token_budget": int(engine.token_budget),
        "eos_token_id": engine.eos_token_id,
        "cache_dtype": str(kv.dtype),
        "kv_dtype": kv.kv_dtype,
        "draft_k": int(engine.draft_k),
        "draft_ngram": int(engine.draft_ngram),
        "prefix_caching": engine.prefix_cache is not None,
        "role": engine.role,
        "max_adapters": (int(engine.adapters.max_adapters)
                         if engine.adapters is not None else 0),
        "lora_rank": (int(engine.adapters.rank)
                      if engine.adapters is not None else 8),
        "lora_alpha": (float(engine.adapters.alpha)
                       if engine.adapters is not None else None),
        "moe_weight_dtype": engine.moe_weight_dtype,
        "sparse_blocks": engine.sparse_blocks,
        "sparse_recent": (int(engine._sparse_recent)
                          if engine._sparse else 2),
        "track_summaries": bool(engine._track_summaries),
        "sampling": dataclasses.asdict(engine.sampling),
        "tensor_parallel": int(getattr(engine, "tensor_parallel", 1)),
        "expert_parallel": int(getattr(engine, "expert_parallel", 1)),
    }
    return cfg


def _serialize_step(engine):
    """Lower + AOT-compile the engine's jitted mixed step against its
    own example arguments and serialize the executable. Goes through
    `._jitted.lower(...)` directly — the AOT path neither populates
    the instrumented wrapper's jit cache nor ticks the compile
    watchdog, so exporting from inside a sanitized test costs no
    budget.

    The compile must be FRESH: on jax 0.4.x, `serialize()` of an
    executable the persistent compilation cache handed back emits a
    payload whose jitted symbol bodies are missing ("Symbols not
    found" at deserialize). Flipping `jax_compilation_cache_dir` is
    not enough on its own — `compilation_cache.is_cache_used()`
    memoizes its verdict process-wide the first time it runs, so the
    dir toggle must be bracketed with `reset_cache()` to force a
    re-evaluation (and again after restoring, so normal compiles
    re-adopt the configured cache)."""
    import jax
    from jax._src import compilation_cache as _cc
    ser = _serialize_mod()
    if ser is None:
        return None
    lowered = engine._step_fn._jitted.lower(*engine.example_step_args())
    cache_dir = jax.config.jax_compilation_cache_dir
    try:
        if cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", None)
            _cc.reset_cache()
        compiled = lowered.compile()
    finally:
        if cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            _cc.reset_cache()
    payload, in_tree, out_tree = ser.serialize(compiled)
    return pickle.dumps({"payload": payload, "in_tree": in_tree,
                         "out_tree": out_tree},
                        protocol=pickle.HIGHEST_PROTOCOL)


def export_bundle(engine, path=None, *, version="v1", seed=0,
                  include_executable=True):
    """Write `engine`'s boot bundle for `version`; returns the bundle
    directory. Weights are the CANONICAL model tensors (pre-cast,
    pre-quantization, pre-TP-permute, `model._gen_tensors()` order):
    the boot replays the engine constructor's own transforms, which
    keeps one weights file valid for every (role, TP) executable in
    the bundle."""
    root = path if path is not None else default_bundle_root()
    bdir = os.path.join(root, str(version))
    os.makedirs(bdir, exist_ok=True)
    tensors = list(engine.model._gen_tensors())
    arrays = [np.asarray(t._data) for t in tensors]
    np.savez(os.path.join(bdir, WEIGHTS),
             **{f"w{i:05d}": a for i, a in enumerate(arrays)})
    manifest = {
        "format": FORMAT,
        "version": str(version),
        "seed": int(seed),
        "model": model_config(engine.model),
        "engine": engine_config(engine),
        "kv_meta": engine.kv.kv_meta(),
        "weights": [{"index": i, "shape": list(a.shape),
                     "dtype": str(a.dtype)}
                    for i, a in enumerate(arrays)],
        "executables": {},
    }
    mpath = os.path.join(bdir, MANIFEST)
    if include_executable:
        blob = _serialize_step(engine)
        if blob is not None:
            role = engine.role
            tp = int(getattr(engine, "tensor_parallel", 1))
            fname = _exec_file(role, tp)
            with open(os.path.join(bdir, fname), "wb") as f:
                f.write(blob)
            manifest["executables"][_exec_key(role, tp)] = fname
    if os.path.exists(mpath):
        # re-export for another (role, TP): merge executable indices,
        # keep the shared config/weights freshly written above
        with open(mpath) as f:
            old = json.load(f)
        merged = dict(old.get("executables", {}))
        merged.update(manifest["executables"])
        manifest["executables"] = merged
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    return bdir


class FleetBundle:
    """A loaded boot bundle: manifest + lazy weights + executables."""

    def __init__(self, path):
        self.path = str(path)
        with open(os.path.join(self.path, MANIFEST)) as f:
            self.manifest = json.load(f)
        if self.manifest.get("format") != FORMAT:
            raise ValueError(
                f"bundle format {self.manifest.get('format')} != "
                f"supported {FORMAT} ({self.path})")
        self._weights = None

    @classmethod
    def load(cls, path):
        return cls(path)

    @property
    def version(self):
        return self.manifest["version"]

    def weights(self):
        """Canonical model-order host arrays (cached)."""
        if self._weights is None:
            z = np.load(os.path.join(self.path, WEIGHTS))
            self._weights = [z[f"w{i:05d}"]
                             for i in range(len(z.files))]
        return self._weights

    def has_executable(self, role="mixed", tp=1):
        return _exec_key(role, tp) in self.manifest["executables"]

    def executable(self, role="mixed", tp=1):
        """Deserialize the (role, tp) step executable into a callable
        that runs WITHOUT compiling; None when the bundle carries no
        executable for that key (or this jax can't deserialize)."""
        ser = _serialize_mod()
        fname = self.manifest["executables"].get(_exec_key(role, tp))
        if ser is None or fname is None:
            return None
        with open(os.path.join(self.path, fname), "rb") as f:
            d = pickle.load(f)
        return ser.deserialize_and_load(d["payload"], d["in_tree"],
                                        d["out_tree"])

    def build_model(self):
        """Reconstruct the model from the manifest and inject the
        bundled weights into its tensors BEFORE any engine sees it —
        the engine constructor then applies its own cast/quantize/
        shard transforms, identical to the exporting engine's."""
        import jax.numpy as jnp

        from ...models.gpt import GPTForGeneration
        model = GPTForGeneration(**self.manifest["model"])
        tensors = list(model._gen_tensors())
        weights = self.weights()
        if len(tensors) != len(weights):
            raise ValueError(
                f"bundle holds {len(weights)} tensors, rebuilt model "
                f"has {len(tensors)} — manifest/model drift")
        for t, w in zip(tensors, weights):
            if tuple(t._data.shape) != tuple(w.shape):
                raise ValueError(
                    f"bundle tensor {tuple(w.shape)} != model tensor "
                    f"{tuple(t._data.shape)}")
            t._data = jnp.asarray(w)
        return model


def boot_engine_from_bundle(bundle, *, aot=True, warm_prefix=None,
                            name=None, model_factory=None,
                            clock=None, **overrides):
    """Construct a ServingEngine (or TPServingEngine for bundles
    exported from one) from a bundle. With `aot=True` and a matching
    executable in the bundle, the deserialized compiled step is
    installed and the replica performs ZERO mixed-step jit compiles.
    `warm_prefix` names a `RadixPrefixCache.spill` file to re-adopt
    (warm boot). Returns the engine, with `weights_version` stamped
    from the bundle."""
    if isinstance(bundle, str):
        bundle = FleetBundle(bundle)
    model = (model_factory() if model_factory is not None
             else bundle.build_model())
    ecfg = dict(bundle.manifest["engine"])
    tp = int(ecfg.pop("tensor_parallel", 1))
    ep = int(ecfg.pop("expert_parallel", 1))
    sampling_cfg = ecfg.pop("sampling", None)
    if sampling_cfg is not None:
        from ..batcher import SamplingConfig
        ecfg["sampling"] = SamplingConfig(**sampling_cfg)
    ecfg["seed"] = int(bundle.manifest.get("seed", 0))
    if clock is not None:
        ecfg["clock"] = clock
    if name is not None:
        ecfg["name"] = name
    ecfg.update(overrides)
    role = ecfg.get("role", "mixed")
    if tp > 1 or ep > 1:
        from ..distributed.tp_engine import TPServingEngine
        engine = TPServingEngine(model, tensor_parallel=tp,
                                 expert_parallel=ep, **ecfg)
    else:
        from ..engine import ServingEngine
        engine = ServingEngine(model, **ecfg)
    engine.weights_version = bundle.version
    if aot:
        fn = bundle.executable(role, tp)
        if fn is not None:
            engine.install_aot_step(fn)
    if warm_prefix is not None and engine.prefix_cache is not None \
            and os.path.exists(warm_prefix):
        engine.prefix_cache.restore(warm_prefix)
    return engine
