"""FleetController: boot / drain / upgrade / retire through the
router's quiesce plane (ISSUE 17 tentpole glue).

One controller operates one `ReplicaRouter` against one (current)
`FleetBundle`. Replica indices are append-only — retirement stops a
replica and marks it down but never reindexes, so in-flight streams,
metric labels and the health plane stay coherent for the fleet's
whole life.

Lifecycle verbs:

* `boot_replica()` — AOT boot from the bundle (zero mixed-step
  compiles), optional warm prefix spill, optional probe prompt whose
  first token closes the measured cold-start window
  (`paddle_tpu_serving_fleet_cold_start_seconds`), then
  `router.add_replica` puts it in rotation.
* `drain(idx)` — quiesce + wait until the replica holds no work
  anywhere on its path (router in-flight, fair queue, live set,
  engine scheduler).
* `retire(idx)` — drain, stop the frontend, spill the prefix cache
  (when a spill_dir is configured), close the engine, mark down.
* `rolling_upgrade(weights, version)` — `upgrade.rolling_upgrade`
  over this router, then a census refresh (the version label
  migrates on `paddle_tpu_serving_fleet_replicas`).
* `scale_up(reason)` / `scale_down(reason)` — the autoscaler's
  actuators; each ticks `fleet_scale_events_total{direction,reason}`.
"""
from __future__ import annotations

import os

from .. import metrics as smetrics
from ...profiler import metrics as _pmetrics
from .export import FleetBundle, boot_engine_from_bundle


class FleetController:
    def __init__(self, router, bundle=None, *, spill_dir=None,
                 clock=None, max_pending=256):
        self.router = router
        self.bundle = (FleetBundle(bundle) if isinstance(bundle, str)
                       else bundle)
        self.spill_dir = spill_dir
        self.clock = clock if clock is not None else router.clock
        self.max_pending = int(max_pending)
        self.retired = set()
        self.booted = []          # indices this controller booted
        self._census_seen = set()
        self._census()

    # ------------------------------------------------------------ state
    def active_replicas(self):
        """Indices in rotation: not retired, not marked down. Reads
        the health plane's down flags rather than probing — a probe
        would misread hand-built fleets whose frontends start lazily,
        and `alive()` marks down as a side effect."""
        return [i for i in range(len(self.router.frontends))
                if i not in self.retired
                and not self.router.health._down[i]]

    def _census(self):
        """Refresh `fleet_replicas{role,version}` from the live fleet;
        label pairs that emptied out are zeroed, not dropped."""
        if not _pmetrics._enabled:
            return
        counts = {}
        for i in self.active_replicas():
            key = (self.router.roles[i], self.router._version(i))
            counts[key] = counts.get(key, 0) + 1
        for key in self._census_seen - set(counts):
            smetrics.FLEET_REPLICAS.labels(*key).set(0)
        for key, n in counts.items():
            smetrics.FLEET_REPLICAS.labels(*key).set(n)
        self._census_seen |= set(counts)

    def _spill_path(self, engine):
        if self.spill_dir is None or engine.prefix_cache is None:
            return None
        os.makedirs(self.spill_dir, exist_ok=True)
        return os.path.join(self.spill_dir,
                            f"prefix_{engine.name}.pkl")

    # ------------------------------------------------------------- boot
    async def boot_replica(self, *, aot=True, warm_prefix=None,
                           name=None, probe_prompt=None,
                           probe_tokens=1, **overrides):
        """Boot one replica from the bundle and add it to rotation.
        Returns its index. `aot=True` installs the bundle's
        deserialized step executable: ZERO mixed-step jit compiles.
        `warm_prefix` re-adopts a prefix spill (warm boot). A
        `probe_prompt` serves `probe_tokens` through the fresh engine
        before rotation so the recorded cold-start spans
        boot-to-first-token (the bench lane's definition)."""
        from ..frontend import ServingFrontend
        if self.bundle is None:
            raise ValueError("boot_replica needs a FleetBundle")
        t0 = self.clock()
        engine = boot_engine_from_bundle(
            self.bundle, aot=aot, warm_prefix=warm_prefix, name=name,
            **overrides)
        if probe_prompt is not None:
            engine.generate_batch([list(probe_prompt)],
                                  max_new_tokens=int(probe_tokens))
        dt = self.clock() - t0
        warm = (warm_prefix is not None
                and engine.prefix_cache is not None
                and engine.prefix_cache.cached_blocks > 0)
        if _pmetrics._enabled:
            smetrics.FLEET_BOOTS.labels("warm" if warm
                                        else "cold").inc()
            smetrics.FLEET_COLD_START.observe(dt)
        fe = ServingFrontend(engine, max_pending=self.max_pending)
        idx = await self.router.add_replica(fe, engine.role)
        self.booted.append(idx)
        self._census()
        return idx

    # ------------------------------------------------------ drain/retire
    async def drain(self, idx, *, poll_s=0.005, timeout_s=30.0):
        """Quiesce replica `idx` and wait for its in-flight work to
        finish on its current weights. The replica stays healthy and
        stays quiesced — callers flip weights or retire next."""
        import asyncio
        self.router.quiesce(idx)
        deadline = self.clock() + float(timeout_s)
        while not self.router.is_drained(idx):
            if self.clock() > deadline:
                raise TimeoutError(
                    f"replica {idx} did not drain within "
                    f"{timeout_s}s")
            await asyncio.sleep(poll_s)

    async def retire(self, idx, *, spill_prefix=None):
        """Drain + stop + close replica `idx` (spilling its prefix
        cache when configured). Its index stays allocated and marked
        down forever. Returns blocks spilled."""
        await self.drain(idx)
        fe = self.router.frontends[idx]
        await fe.stop()
        spill = (spill_prefix if spill_prefix is not None
                 else self._spill_path(fe.engine))
        spilled = fe.engine.close(spill_prefix=spill)
        self.retired.add(idx)
        self.router.health.mark_down(idx)
        self._census()
        return spilled

    # ---------------------------------------------------------- upgrade
    async def rolling_upgrade(self, weights, version, **kw):
        """Flip the fleet to (`weights`, `version`) one drained
        replica at a time (`upgrade.rolling_upgrade`); returns flipped
        indices. The bundle reference is NOT rewritten — export a new
        bundle per version for future boots."""
        from .upgrade import rolling_upgrade
        flipped = await rolling_upgrade(self.router, weights, version,
                                        **kw)
        self._census()
        return flipped

    # ------------------------------------------------------------ scale
    async def scale_up(self, reason, **boot_kw):
        idx = await self.boot_replica(**boot_kw)
        if _pmetrics._enabled:
            smetrics.FLEET_SCALE_EVENTS.labels("up",
                                               str(reason)).inc()
        return idx

    async def scale_down(self, reason):
        """Retire the most recently booted active replica (LIFO keeps
        the original hand-built fleet intact at min scale)."""
        active = set(self.active_replicas())
        cands = [i for i in self.booted if i in active]
        idx = cands[-1] if cands else max(active)
        await self.retire(idx)
        if _pmetrics._enabled:
            smetrics.FLEET_SCALE_EVENTS.labels("down",
                                               str(reason)).inc()
        return idx
