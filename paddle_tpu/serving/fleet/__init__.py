"""paddle_tpu.serving.fleet — the fleet control plane (ISSUE 17).

Replicas as cattle, not pets. Three coupled pieces on top of the
serving engine / router / SLO plane:

* `export` — versioned AOT boot bundles: model config, weight
  manifest, kv_meta, engine knobs and the mixed step's SERIALIZED
  compiled executable per (role, tensor_parallel), written next to
  the persistent kernel-autotune cache. `boot_engine_from_bundle`
  brings a ServingEngine up with ZERO `serving_mixed_step` jit
  compiles (watchdog-asserted by tools/fleet_smoke.py).
* `upgrade` — live weight swap: one jitted budget-1
  `serving_weight_swap` cast per engine flips a drained replica to a
  new checkpoint version between steps; the controller rolls the
  fleet version-by-version through the router's quiesce plane.
* `autoscaler` — SLO-burn-driven replica count re-planning with the
  calibrated-cost-model discipline of `parallel.auto_tuner`
  (predicted TTFT/inter-token from queue depth, token budgets and
  measured step times; sustained-burn + cooldown hysteresis).

`controller.FleetController` ties them to a live `ReplicaRouter`.
See docs/DEPLOYMENT.md for the bundle format and lifecycle contract.
"""
from . import autoscaler  # noqa: F401
from . import controller  # noqa: F401
from . import export  # noqa: F401
from . import upgrade  # noqa: F401
from .autoscaler import AutoscalerPolicy, SLOAutoscaler  # noqa: F401
from .controller import FleetController  # noqa: F401
from .export import (FleetBundle, boot_engine_from_bundle,  # noqa: F401
                     export_bundle)
from .upgrade import weights_from_model  # noqa: F401

__all__ = [
    "FleetBundle", "export_bundle", "boot_engine_from_bundle",
    "FleetController", "SLOAutoscaler", "AutoscalerPolicy",
    "weights_from_model", "export", "upgrade", "autoscaler",
    "controller",
]
