"""Live weight swap / rolling upgrade (ISSUE 17 (b)).

The mechanism lives in `ServingEngine.swap_weights`: ONE jitted
budget-1 `serving_weight_swap` cast per engine (the same discipline as
`serving_adapter_load`) replaces the parameter set between steps with
a new same-architecture checkpoint — the exact compute-dtype transform
engine construction applies, so a swapped engine is bit-identical to
one built from the new checkpoint, and the mixed step's compiled
executable keys unchanged (no recompile, ever).

This module holds the checkpoint plumbing and the fleet-level rolling
policy the controller drives:

    for each replica, one at a time:
        router.quiesce(idx)        # no NEW dispatches land here
        wait until router.is_drained(idx)   # in-flight finish on OLD
        engine.swap_weights(new, version)   # idle engine, one cast
        router.unquiesce(idx)      # back in rotation on NEW weights

In-flight requests complete on their original weights; post-flip
requests see the new version; with >= 2 replicas the fleet never
stops serving. Mid-upgrade the fleet's aggregate output is
token-identical to a same-version fleet because every request runs
start-to-finish on exactly one version (tools/fleet_smoke.py asserts
this against static v1/v2 reference outputs).
"""
from __future__ import annotations

import numpy as np


def weights_from_model(model):
    """Canonical checkpoint arrays from a (new-version) model:
    `model._gen_tensors()` order, host-side — exactly what
    `ServingEngine.swap_weights` and bundle export consume."""
    return [np.asarray(t._data) for t in model._gen_tensors()]


def weights_from_bundle(bundle):
    """Canonical checkpoint arrays from a `FleetBundle` (or path)."""
    from .export import FleetBundle
    if isinstance(bundle, str):
        bundle = FleetBundle(bundle)
    return bundle.weights(), bundle.version


async def rolling_upgrade(router, weights, version, *,
                          drain_poll_s=0.005, drain_timeout_s=30.0,
                          replicas=None, on_flip=None):
    """Flip every live replica of `router` to (`weights`, `version`),
    one at a time, through the quiesce/drain protocol above. Returns
    the list of replica indices flipped. `replicas` restricts the roll
    (default: every non-quiesced live replica); `on_flip(idx)` fires
    after each replica returns to rotation.

    Single-replica fleets are refused: with nothing left in rotation
    during the drain, new requests would fail instead of landing on a
    not-yet-flipped sibling — boot a second replica first (the
    controller's scale path does exactly that)."""
    import asyncio

    from .. import metrics as smetrics
    from ...profiler import metrics as _pmetrics

    targets = [i for i in range(len(router.frontends))
               if i not in router._quiesced and router.health.alive(i)
               ] if replicas is None else list(replicas)
    if len(targets) < 2 and replicas is None:
        raise ValueError(
            "rolling upgrade needs >= 2 replicas in rotation so the "
            "fleet keeps serving through each drain")
    flipped = []
    for idx in targets:
        router.quiesce(idx)
        try:
            deadline = router.clock() + float(drain_timeout_s)
            while not router.is_drained(idx):
                if router.clock() > deadline:
                    raise TimeoutError(
                        f"replica {idx} did not drain within "
                        f"{drain_timeout_s}s")
                await asyncio.sleep(drain_poll_s)
            # drained: the frontend's step loop only touches the
            # engine when the scheduler has work, so the swap runs
            # race-free from here
            router.frontends[idx].engine.swap_weights(weights, version)
        finally:
            router.unquiesce(idx)
        flipped.append(idx)
        if _pmetrics._enabled:
            smetrics.FLEET_UPGRADES.inc()
        if on_flip is not None:
            on_flip(idx)
    return flipped
