"""paddle_tpu.serving — paged KV-cache + continuous-batching engine.

The multi-request serving subsystem: a block-paged, refcounted KV
cache (`kv_cache`), a radix-tree prefix cache for cross-request KV
reuse (`prefix_cache`), a FIFO/preemption scheduler (`scheduler`),
token-budget batching + sampling heads + the tenant-fair admission
queue (`batcher`), serving metrics (`metrics`), the single-compile
mixed-step `ServingEngine` (`engine`), the asyncio multi-tenant
ingress `ServingFrontend` (`frontend`), and the distributed layer
(`distributed`): the tensor-parallel `TPServingEngine` and the
multi-replica prefix-affinity `ReplicaRouter`, plus the fleet
control plane (`fleet`): versioned AOT boot bundles, rolling weight
upgrades and the SLO-burn autoscaler. See docs/SERVING.md for the
slot protocol, prefix-cache and distributed semantics and
docs/DEPLOYMENT.md for the fleet lifecycle.

`engine`/`frontend` (and their model deps) load lazily so the light
modules here can be imported from `incubate/nn/generation.py` without
cycles.
"""
from . import adapters  # noqa: F401
from . import batcher  # noqa: F401
from . import kv_cache  # noqa: F401
from . import metrics  # noqa: F401
from . import prefix_cache  # noqa: F401
from . import scheduler  # noqa: F401
from . import slo  # noqa: F401
from . import tracing  # noqa: F401
from .adapters import AdapterCache  # noqa: F401
from .batcher import FairQueue, SamplingConfig  # noqa: F401
from .kv_cache import BlockAllocator, PagedKVCache  # noqa: F401
from .prefix_cache import RadixPrefixCache  # noqa: F401
from .scheduler import Request, Scheduler  # noqa: F401
from .slo import SLOConfig, SLOMonitor  # noqa: F401
from .tracing import RequestTracer, StepFlightRecorder  # noqa: F401

__all__ = [
    "SamplingConfig", "BlockAllocator", "PagedKVCache", "Request",
    "Scheduler", "ServingEngine", "ServingFrontend", "FairQueue",
    "RadixPrefixCache", "AdapterCache", "adapters", "batcher",
    "kv_cache", "metrics", "scheduler",
    "prefix_cache", "engine", "frontend", "distributed", "fleet",
    "sparse_budget", "TPServingEngine", "ReplicaRouter",
    "FleetController",
    "tracing", "slo", "RequestTracer", "StepFlightRecorder",
    "SLOConfig", "SLOMonitor",
]

_LAZY = {
    "ServingEngine": ("engine", "ServingEngine"),
    "engine": ("engine", None),
    "ServingFrontend": ("frontend", "ServingFrontend"),
    "frontend": ("frontend", None),
    "distributed": ("distributed", None),
    "TPServingEngine": ("distributed", "TPServingEngine"),
    "ReplicaRouter": ("distributed", "ReplicaRouter"),
    "fleet": ("fleet", None),
    "FleetController": ("fleet", "FleetController"),
    "sparse_budget": ("sparse_budget", None),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is not None:
        import importlib
        import sys
        modname, attr = entry
        mod = importlib.import_module(f"{__name__}.{modname}")
        pkg = sys.modules[__name__]
        setattr(pkg, modname, mod)
        if attr is not None:
            setattr(pkg, attr, getattr(mod, attr))
        return getattr(pkg, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
