"""paddle_tpu.serving — paged KV-cache + continuous-batching engine.

The first multi-request subsystem: a block-paged KV cache with fixed
slot tables (`kv_cache`), a FIFO/preemption scheduler (`scheduler`),
token-budget batching + sampling heads (`batcher`), serving metrics
(`metrics`), and the single-compile mixed-step `ServingEngine`
(`engine`). See docs/SERVING.md for the slot protocol.

`engine` (and its model deps) load lazily so the light modules here
can be imported from `incubate/nn/generation.py` without cycles.
"""
from . import batcher  # noqa: F401
from . import kv_cache  # noqa: F401
from . import metrics  # noqa: F401
from . import scheduler  # noqa: F401
from .batcher import SamplingConfig  # noqa: F401
from .kv_cache import BlockAllocator, PagedKVCache  # noqa: F401
from .scheduler import Request, Scheduler  # noqa: F401

__all__ = [
    "SamplingConfig", "BlockAllocator", "PagedKVCache", "Request",
    "Scheduler", "ServingEngine", "batcher", "kv_cache", "metrics",
    "scheduler", "engine",
]


def __getattr__(name):
    if name in ("ServingEngine", "engine"):
        import importlib
        import sys
        mod = importlib.import_module(__name__ + ".engine")
        pkg = sys.modules[__name__]
        pkg.engine = mod
        pkg.ServingEngine = mod.ServingEngine
        return getattr(pkg, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
