"""Radix-tree prefix KV cache over the paged block pools.

Multi-tenant traffic is dominated by shared prefixes — system prompts,
few-shot templates, chat history replayed on every turn. The K/V of a
token depends only on the token ids before it, so two requests whose
prompts share a block-aligned prefix can share the PHYSICAL KV blocks
of that prefix: the radix tree maps token runs to block lists, and
admission walks it so the scheduler's chunked prefill starts at the
first uncached token instead of position 0 (the vLLM/SGLang
"automatic prefix caching" idea on top of PR 2's block pools).

Structure and invariants:

* **Node = block-aligned token run.** Every edge holds `tokens`
  (a multiple of `block_size` ids) plus the matching `blocks`; children
  are keyed by their first block's token tuple, so siblings always
  diverge within their first block. Lookup and insert split nodes at
  block boundaries, classic radix style.
* **Reference counts** live in `kv_cache.BlockAllocator`: the tree
  holds ONE reference per cached block, and every slot table that
  adopted a block holds another. A block returns to the free list only
  when its last owner (tree or slot) lets go — so preemption
  (`release_slot`) and speculative rollback (`truncate_slot`) just
  drop the slot's reference and never corrupt a shared prefix.
* **Locks** (`node.lock`) count resident requests whose slot tables
  adopted the node's blocks; locked nodes are never evicted. The lock
  is released when the slot is freed (finish / preempt / expire /
  cancel).
* **Eviction is LRU over refcount-0 leaves**, integrated with the
  free list: `PagedKVCache._alloc` calls `evict()` when the free list
  runs dry, so cached-but-idle blocks are reclaimed before anyone is
  preempted. Evicting a leaf may expose its parent as the next
  candidate.
* **Copy-on-write** when a request extends a shared block: matching is
  whole-block, but the last prompt token must always be RE-FED (its
  hidden state samples the first output), so when the entire prompt is
  covered by cached blocks the first token to feed lands INSIDE the
  last shared block. The slot then gets a private device-side copy of
  that block (`kv_cache.cow_block`) and writes there; every other
  reader keeps the original.

Correctness never depends on the tree: a cold cache (or one evicted to
nothing) degrades to PR 2 behaviour, and outputs are token-identical
either way because cached K/V is exactly what re-prefilling the same
tokens through the same compiled step would write.

Disaggregated serving (docs/SERVING.md) needs NO code here either, by
the same argument: a migrated-away request's slot releases through the
ordinary `release_slot`/`unlock_slot` path — shared prefix blocks it
adopted stay cached on the SOURCE replica (the tree holds its own
refcount), so the prefill replica that published a prompt head keeps
serving it to future same-head requests after every handoff; and
blocks imported on the destination are bit-exact copies of what
re-prefilling would have written there, so the destination's
finish-time `insert` publishes a valid chat-turn prefix built from
transported blocks.

Quantized pools (`PagedKVCache(kv_dtype="int8")`) need NO code here:
the per-entry-per-head scale arrays are indexed by the same
`(block, offset)` coordinates as the K/V bytes, so adoption shares
scale rows by sharing block ids, `cow_block` copies the scale columns
inside its one jitted executable, and the token-identity argument
above still holds because quantization is a pure per-token function
(see kv_cache.PagedKVCache) — asserted by the int8 prefix/CoW parity
cells in tests/test_paged_kernels.py.
"""
from __future__ import annotations

import heapq
import itertools


class RadixNode:
    __slots__ = ("parent", "children", "tokens", "blocks", "lock",
                 "stamp")

    def __init__(self, parent, tokens, blocks):
        self.parent = parent
        self.children = {}       # first-block token tuple -> RadixNode
        self.tokens = tuple(tokens)   # len == len(blocks) * block_size
        self.blocks = list(blocks)
        self.lock = 0            # resident slots using these blocks
        self.stamp = 0           # LRU clock at last touch

    @property
    def is_leaf(self):
        return not self.children


class RadixPrefixCache:
    """Block-aligned radix tree over one `PagedKVCache`'s pools."""

    def __init__(self, kv):
        self.kv = kv
        self.bs = kv.block_size
        self.root = RadixNode(None, (), ())
        self.root.lock = 1               # the root is never evictable
        self._slot_nodes = [[] for _ in range(kv.max_slots)]
        self._tick = itertools.count(1)
        # raw counters (always on; the engine mirrors deltas into the
        # metrics registry under the one-branch discipline)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evictions = 0               # blocks reclaimed by LRU
        self.cow_copies = 0
        kv.prefix_cache = self

    # ------------------------------------------------------------- stats
    @property
    def cached_blocks(self):
        """Blocks currently held by tree references."""
        total = 0
        stack = [self.root]
        while stack:
            n = stack.pop()
            total += len(n.blocks)
            stack.extend(n.children.values())
        return total

    def hit_ratio(self):
        t = self.hit_tokens + self.miss_tokens
        return self.hit_tokens / t if t else 0.0

    # ------------------------------------------------------------- match
    def _touch(self, node):
        node.stamp = next(self._tick)

    def _key(self, tokens, at):
        return tuple(tokens[at:at + self.bs])

    def _split(self, node, keep_blocks):
        """Split `node` so its edge holds exactly `keep_blocks` blocks;
        the remainder moves to a child. Locks/stamps are inherited by
        BOTH halves (a lock on the long edge locked every block in it)."""
        cut = keep_blocks * self.bs
        child = RadixNode(node, node.tokens[cut:],
                          node.blocks[keep_blocks:])
        child.children = node.children
        for c in child.children.values():
            c.parent = child
        child.lock = node.lock
        child.stamp = node.stamp
        node.tokens = node.tokens[:cut]
        node.blocks = node.blocks[:keep_blocks]
        node.children = {self._key(child.tokens, 0): child}
        if node.lock:
            # every slot holding the long edge now holds BOTH halves,
            # so its unlock releases both
            for lst in self._slot_nodes:
                if node in lst:
                    lst.append(child)
        return node

    def _walk(self, tokens, max_blocks, split=True):
        """Walk the tree over `tokens`, matching at most `max_blocks`
        whole blocks. Returns (nodes, blocks, n_blocks): the matched
        path (root excluded), their blocks in order, and the count.
        With `split`, a partial edge match splits the node so the path
        covers EXACTLY the matched blocks."""
        node = self.root
        nodes, blocks = [], []
        at = 0                           # matched blocks so far
        while at < max_blocks:
            child = node.children.get(self._key(tokens, at * self.bs))
            if child is None:
                break
            nb = len(child.blocks)
            take = 0
            while take < nb and at + take < max_blocks and \
                    tuple(tokens[(at + take) * self.bs:
                                 (at + take + 1) * self.bs]) \
                    == child.tokens[take * self.bs:(take + 1) * self.bs]:
                take += 1
            if take == 0:
                break
            if take < nb:
                if split:
                    child = self._split(child, take)
                    nodes.append(child)
                    blocks.extend(child.blocks)
                    at += take
                # partial edge: nothing deeper can match
                break
            nodes.append(child)
            blocks.extend(child.blocks)
            at += nb
            node = child
        return nodes, blocks, at

    # --------------------------------------------------------- admission
    def lookup_and_adopt(self, slot, tokens):
        """Admission-time lookup for `slot`'s runtime prompt. Adopts
        every cached block covering the prompt head into the slot's
        table (shared, refcounted), CoWs the partially-extended block
        when the hit ends mid-block, locks the matched path against
        eviction, and returns the number of cached tokens — the
        scheduler feeds the prompt from there."""
        n = len(tokens)
        usable = n - 1          # the LAST token is always re-fed
        if usable <= 0:
            self.miss_tokens += n
            return 0
        want_blocks = -(-usable // self.bs)      # ceil: CoW may extend
        nodes, blocks, got = self._walk(tokens, want_blocks)
        hit = min(got * self.bs, usable)
        full = hit // self.bs
        partial = hit % self.bs
        # lock + LRU-touch the matched path BEFORE any allocation: the
        # CoW below can trigger an eviction pass, which must not pick
        # the very nodes this request just hit
        for node in nodes:
            node.lock += 1
            self._touch(node)
        self._slot_nodes[slot].extend(nodes)
        if full:
            self.kv.adopt_blocks(slot, blocks[:full])
        if partial:
            # the hit ends inside blocks[full]: adopt + private copy so
            # the re-fed tail can write without touching the shared copy
            self.kv.adopt_blocks(slot, [blocks[full]])
            if self.kv.cow_block(slot, full):
                self.cow_copies += 1
            else:
                # pool dry even after eviction: fall back to the
                # block-aligned hit and recompute the partial tail
                self.kv.truncate_slot(slot, full * self.bs)
                hit = full * self.bs
        self.hit_tokens += hit
        self.miss_tokens += n - hit
        return hit

    def unlock_slot(self, slot):
        """Drop the slot's eviction locks (slot freed: finish, preempt,
        expiry or cancellation). Block references were already dropped
        by `release_slot`; the blocks stay cached until evicted."""
        for node in self._slot_nodes[slot]:
            node.lock -= 1
        self._slot_nodes[slot] = []

    # ------------------------------------------------------------ insert
    def insert(self, slot, tokens):
        """Cache `slot`'s written K/V for `tokens` (full blocks only).
        Called at prefill completion (prompt reuse) and at finish
        (prompt + generated output, e.g. chat history). Already-cached
        prefixes dedup against the existing tree — only the new suffix
        takes tree references; the slot's own duplicate blocks for a
        deduped range simply drop off when the slot releases."""
        nblocks = len(tokens) // self.bs
        if nblocks == 0:
            return 0
        nodes, _, got = self._walk(tokens, nblocks)
        if got >= nblocks:
            return 0
        row = self.kv.slot_blocks(slot)
        new_blocks = row[got:nblocks]
        new_tokens = tuple(tokens[got * self.bs:nblocks * self.bs])
        if len(new_blocks) != nblocks - got:
            return 0                      # slot shorter than claimed
        # the walk split any partially-matching edge, so the deepest
        # matched node is exactly the attach parent
        parent = nodes[-1] if nodes else self.root
        node = RadixNode(parent, new_tokens, new_blocks)
        self._touch(node)
        parent.children[self._key(new_tokens, 0)] = node
        self.kv.allocator.incref(new_blocks)
        return len(new_blocks)

    # ---------------------------------------------------------- eviction
    def evict(self, need_blocks):
        """Free at least `need_blocks` blocks by evicting LRU unlocked
        leaves whose blocks the tree holds the ONLY reference to.
        Returns the number of blocks actually returned to the free
        list.

        Leaves whose blocks a resident slot still references (e.g. the
        writer that published them — it holds block refs but no node
        lock) are skipped: dropping the tree's reference there would
        free NOTHING while destroying a hot prefix; if the pool is
        genuinely full of in-use blocks, failing here so the scheduler
        preempts is the correct outcome."""
        if need_blocks <= 0:
            return 0
        heap = []
        seq = itertools.count()

        def evictable(n):
            return (n.is_leaf and n.lock == 0 and n.parent is not None
                    and all(self.kv.allocator.refcount(b) == 1
                            for b in n.blocks))

        def push(n):
            if evictable(n):
                heapq.heappush(heap, (n.stamp, next(seq), n))

        stack = [self.root]
        while stack:
            n = stack.pop()
            push(n)
            stack.extend(n.children.values())
        freed = 0
        while heap and freed < need_blocks:
            _, _, node = heapq.heappop(heap)
            if not evictable(node):
                continue                  # stale heap entry
            self.kv.allocator.free(node.blocks)
            freed += len(node.blocks)
            parent = node.parent
            del parent.children[self._key(node.tokens, 0)]
            node.parent = None
            push(parent)
        self.evictions += freed
        return freed

    def evict_all(self):
        """Drop every unlocked cached block (shutdown / tests)."""
        total = 0
        while True:
            freed = self.evict(self.kv.num_blocks)
            total += freed
            if freed == 0:
                return total

    # ------------------------------------------------------ persistence
    def spill(self, path):
        """Serialize the radix tree + its cached KV payloads to a host
        file (ISSUE 17 satellite: prefix persistence across engine
        restarts). Each node spills its token run plus
        `kv.export_blocks` payloads — the SAME host representation the
        disaggregated-serving codec ships — prefixed with `kv_meta()`
        so `restore` can refuse a mismatched pool instead of
        corrupting one. Read-only on the tree; parents precede
        children in the record list so restore can rebuild edges in
        one pass. Returns the number of blocks spilled."""
        import pickle
        order = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n is not self.root:
                order.append(n)
            stack.extend(n.children.values())
        index = {self.root: -1}
        for i, n in enumerate(order):
            index[n] = i
        records = []
        blocks = 0
        for n in order:
            records.append({
                "parent": index[n.parent],
                "tokens": tuple(n.tokens),
                "arrays": self.kv.export_blocks(n.blocks),
            })
            blocks += len(n.blocks)
        with open(path, "wb") as f:
            pickle.dump({"format": 1, "kv_meta": self.kv.kv_meta(),
                         "nodes": records}, f)
        return blocks

    def restore(self, path):
        """Re-adopt a spilled tree into THIS cache's pool: allocate
        fresh blocks (the allocation's refcount-1 is exactly the
        tree's reference), scatter the payloads back with
        `kv.import_blocks`, and rebuild the radix edges in spill
        order. All-or-nothing: a `kv_meta` mismatch raises, and a pool
        too small for the whole spill restores NOTHING (a partial tree
        would orphan subtrees). Only valid on an empty tree (warm
        boot). Returns the number of blocks restored."""
        import pickle
        with open(path, "rb") as f:
            payload = pickle.load(f)
        if payload.get("kv_meta") != self.kv.kv_meta():
            raise ValueError(
                f"prefix spill kv_meta {payload.get('kv_meta')} does "
                f"not match this pool's {self.kv.kv_meta()}")
        if self.root.children:
            raise ValueError(
                "restore() needs an empty prefix tree (warm boot)")
        records = payload["nodes"]
        need = sum(len(r["arrays"][0]) for r in records)
        if need == 0 or need > self.kv.allocator.num_free:
            return 0
        built = []
        for rec in records:
            n_blocks = len(rec["arrays"][0])
            ids = self.kv.allocator.alloc(n_blocks)
            self.kv.import_blocks(ids, rec["arrays"])
            parent = (self.root if rec["parent"] < 0
                      else built[rec["parent"]])
            node = RadixNode(parent, rec["tokens"], ids)
            self._touch(node)
            parent.children[self._key(node.tokens, 0)] = node
            built.append(node)
        return need
