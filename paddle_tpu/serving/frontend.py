"""Async multi-tenant serving frontend over the continuous-batching engine.

The ingress layer the engine was missing: PR 2-3 built a synchronous
`ServingEngine` that a single caller drives (`generate_batch` blocks
until every request finishes). `ServingFrontend` turns it into a
service: an asyncio API (`submit()` awaits the full completion,
`stream()` yields per-token) over ONE background step-loop task that
drives the engine's single compiled mixed step, with

* **admission + backpressure** — a bounded `batcher.FairQueue`;
  `submit`/`stream` await for space when the frontend is saturated
  instead of growing an unbounded queue, and lanes are served
  round-robin per tenant so one chatty tenant cannot starve the rest;
* **cancellation** — cancelling the consumer (or `handle.cancel()`)
  reclaims the request's slot, KV blocks and prefix-cache locks at the
  next step boundary;
* **deadlines** — `timeout=` maps to the scheduler's absolute deadline;
  expiry surfaces as `DeadlineExceeded` on the awaiting caller.

Threading model: ALL frontend and engine state is mutated from the
event-loop thread, except `engine.step()` itself which runs in the
default executor so the loop stays responsive during device work.
While a step is in flight the loop only ever *flags* intent
(submissions land in the fair queue, cancellations set a bool); the
step-loop task applies those flags between steps. That keeps the
engine single-threaded in effect — no locks, and the mixed step still
compiles exactly once.

Outputs are token-identical to the cache-off, single-request
`generate()` path: the frontend adds scheduling, never math
(tests/test_frontend.py asserts parity and the single compile).
"""
from __future__ import annotations

import asyncio

from .batcher import FairQueue

_DONE = object()


class DeadlineExceeded(Exception):
    """The request's deadline passed before it finished."""


class RequestCancelled(Exception):
    """The request was cancelled before it finished."""


class FrontendClosed(Exception):
    """The frontend was stopped while the request was in flight."""


class RequestMigrated(Exception):
    """The request left this replica mid-stream (prefill handoff or a
    load-shedding migration). Carries the `MigrationTicket` — KV block
    payload plus host state — the router re-submits elsewhere; tokens
    already streamed stay delivered (the ticket's `output` includes
    them, so the destination publishes only what comes after)."""

    def __init__(self, ticket):
        super().__init__("request migrated away")
        self.ticket = ticket


class FrontendHandle:
    """One in-flight request as seen by a caller."""

    def __init__(self, prompt, max_new_tokens, tenant, deadline,
                 adapter_id=None, trace_id=None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.tenant = tenant
        self.deadline = deadline
        self.adapter_id = adapter_id      # LoRA adapter (None = base)
        # fleet-wide tracing (serving.tracing): the router mints the
        # trace id at dispatch and it rides the handle to the engine
        # submit, so the request's spans stitch onto the fleet trace
        self.trace_id = trace_id
        self.req = None               # scheduler Request once admitted
        self.queue = asyncio.Queue()  # tokens, then _DONE / exception
        self.published = 0
        self.cancel_requested = False
        self.terminal = False
        # disaggregated serving (docs/SERVING.md): inbound migrations
        # carry their ticket until engine admission; prefill handoffs
        # stream completed blocks through `on_blocks`; `shed()` flags
        # live decodes for extraction at the next step boundary
        self.ticket = None
        self.on_blocks = None
        self.extract_requested = False

    @property
    def tokens(self):
        """Tokens generated so far (live view once admitted)."""
        return list(self.req.output) if self.req is not None else []

    def cancel(self):
        """Request cancellation; applied at the next step boundary."""
        self.cancel_requested = True


class ServingFrontend:
    """Bounded async ingress over one `ServingEngine`.

    Usage::

        frontend = ServingFrontend(engine, max_pending=64)
        async with frontend:
            toks = await frontend.submit(prompt, max_new_tokens=64)
            async for tok in frontend.stream(prompt2, tenant="b"):
                ...
    """

    #: backoff for unproductive iterations (engine reported no work
    #: done while work remained — e.g. expiry-only rounds): the loop
    #: sleeps IDLE_BACKOFF_S doubling up to IDLE_BACKOFF_MAX_S instead
    #: of hammering the executor with no-op engine.step calls
    IDLE_BACKOFF_S = 0.001
    IDLE_BACKOFF_MAX_S = 0.05

    def __init__(self, engine, *, max_pending=256, engine_queue_depth=None):
        self.engine = engine
        self.step_calls = 0           # executor dispatches of engine.step
        self._fair = FairQueue(max_pending)
        # how many requests may sit in the ENGINE's FIFO beyond the
        # resident slots: deep enough to keep every slot busy the
        # moment one frees, shallow enough that fairness (which lives
        # in the frontend lanes) still governs admission order
        self._engine_depth = (engine.kv.max_slots if engine_queue_depth
                              is None else int(engine_queue_depth))
        self._live = []               # handles admitted to the engine
        self._wake = asyncio.Event()
        self._space = asyncio.Event()
        self._task = None
        self._closed = False

    # ---------------------------------------------------------- lifecycle
    async def start(self):
        if self._task is None:
            self._closed = False
            self._task = asyncio.get_running_loop().create_task(
                self._step_loop())
        return self

    async def stop(self):
        """Stop the step loop; in-flight requests get FrontendClosed."""
        self._closed = True
        self._wake.set()
        self._space.set()     # release backpressure waiters to fail
        if self._task is not None:
            try:
                await self._task
            finally:
                self._task = None
        err = FrontendClosed("frontend stopped")
        while True:
            handle = self._fair.pop()
            if handle is None:
                break
            self._finish_handle(handle, err)
        for handle in list(self._live):
            if handle.req is not None:
                self.engine.cancel(handle.req)
            self._finish_handle(handle, err)
        self._live.clear()

    async def __aenter__(self):
        return await self.start()

    async def __aexit__(self, *exc):
        await self.stop()

    # ------------------------------------------------------------ intake
    async def _enqueue(self, prompt, max_new_tokens, tenant, timeout,
                       adapter_id=None, trace_id=None):
        deadline = (self.engine.clock() + float(timeout)
                    if timeout is not None else None)
        handle = FrontendHandle(list(prompt), int(max_new_tokens),
                                str(tenant), deadline,
                                adapter_id=adapter_id,
                                trace_id=trace_id)
        return await self._enqueue_handle(handle)

    async def _enqueue_handle(self, handle):
        if self._closed or self._task is None:
            raise FrontendClosed("frontend is not running")
        deadline = handle.deadline
        while not self._fair.push(handle.tenant, handle):
            # bounded queue full: wait until the step loop drains
            # space — but never past the request's own deadline (a
            # handle not yet in the fair queue is invisible to the
            # admission-time expiry checks)
            self._space.clear()
            if deadline is not None:
                remaining = deadline - self.engine.clock()
                if remaining <= 0:
                    raise DeadlineExceeded()
                try:
                    await asyncio.wait_for(self._space.wait(), remaining)
                except asyncio.TimeoutError:
                    raise DeadlineExceeded() from None
            else:
                await self._space.wait()
            if self._closed:
                raise FrontendClosed("frontend stopped while waiting")
        self._wake.set()
        return handle

    async def submit(self, prompt, max_new_tokens=32, *,
                     tenant="default", timeout=None, adapter_id=None):
        """Run one request to completion; returns its generated token
        ids. Cancelling the awaiting task cancels the request.
        `adapter_id` selects a registered LoRA adapter (None = base)."""
        out = []
        async for tok in self.stream(prompt, max_new_tokens,
                                     tenant=tenant, timeout=timeout,
                                     adapter_id=adapter_id):
            out.append(tok)
        return out

    async def stream(self, prompt, max_new_tokens=32, *,
                     tenant="default", timeout=None, adapter_id=None,
                     on_admitted=None, on_blocks=None, trace_id=None):
        """Async generator of generated tokens, one per decode step
        (speculative acceptance can deliver several per step). Closing
        the generator — or cancelling its consumer — cancels the
        request and reclaims its resources. `on_admitted` (if given)
        is called once the request is in the fair queue — i.e. visible
        to this frontend's own accounting; the router uses it to stop
        double-counting the dispatch in its load estimate.

        `on_blocks` (disaggregated serving) is called after each step
        with a `BlockChunk` of KV blocks the prefill completed since
        the last call — the router ships them ahead to the handoff
        destination. On a prefill-role engine the stream ends with
        `RequestMigrated(ticket)` once the first token is sampled."""
        handle = await self._enqueue(prompt, max_new_tokens, tenant,
                                     timeout, adapter_id=adapter_id,
                                     trace_id=trace_id)
        handle.on_blocks = on_blocks
        if on_admitted is not None:
            on_admitted()
        async for tok in self._consume(handle):
            yield tok

    async def stream_ticket(self, ticket, *, on_admitted=None):
        """Admit a migrated-in request (disaggregated serving): the
        ticket's KV blocks are imported at engine admission and tokens
        stream from where the source replica left off — `published`
        starts past the ticket's already-delivered output, so nothing
        is re-sent. Deadline/tenant/backpressure semantics match
        `stream` (the ticket carries the original absolute deadline)."""
        handle = FrontendHandle(list(ticket.prompt),
                                int(ticket.max_new_tokens),
                                str(ticket.tenant), ticket.deadline,
                                adapter_id=getattr(ticket,
                                                   "adapter_id", None),
                                trace_id=getattr(ticket,
                                                 "trace_id", None))
        handle.ticket = ticket
        handle.published = len(ticket.output)
        await self._enqueue_handle(handle)
        if on_admitted is not None:
            on_admitted()
        async for tok in self._consume(handle):
            yield tok

    async def _consume(self, handle):
        try:
            while True:
                item = await handle.queue.get()
                if item is _DONE:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            if not handle.terminal:
                handle.cancel()
                self._wake.set()

    # --------------------------------------------------------- step loop
    def _finish_handle(self, handle, outcome):
        """Publish the terminal outcome (sentinel or exception)."""
        if handle.terminal:
            return
        handle.terminal = True
        handle.queue.put_nowait(outcome)

    def _apply_cancellations(self):
        # cancelled before admission: drop from the fair queue now so
        # the slot of backpressure it held frees immediately
        queued = [h for h in self._fair.items() if h.cancel_requested]
        for handle in queued:
            self._fair.remove(handle)
            self._finish_handle(handle, RequestCancelled())
        if queued:
            self._space.set()
        for handle in list(self._live):
            if handle.cancel_requested and not handle.terminal:
                self.engine.cancel(handle.req)
                self._live.remove(handle)
                self._finish_handle(handle, RequestCancelled())

    def _admit_pending(self):
        """Fair-drain the frontend queue into the engine, keeping its
        FIFO shallow so frontend fairness governs admission order."""
        sch = self.engine.scheduler
        now = self.engine.clock()
        while len(sch.queue) < self._engine_depth:
            handle = self._fair.pop()
            if handle is None:
                break
            if handle.cancel_requested:
                self._finish_handle(handle, RequestCancelled())
                continue
            # >= (not >): the idle wait below sleeps max(0, deadline -
            # now), so a handle expiring exactly NOW must be expired on
            # this pass — a strict > would zero-delay-loop until the
            # clock ticks past it (forever under a frozen test clock)
            if handle.deadline is not None and now >= handle.deadline:
                self._finish_handle(handle, DeadlineExceeded())
                continue
            try:
                if handle.ticket is not None:
                    # migrated-in request: block import happens at the
                    # scheduler's next plan, not here — engine state
                    # only mutates between steps either way
                    handle.req = self.engine.submit_migrated(
                        handle.ticket)
                    handle.ticket = None
                else:
                    handle.req = self.engine.submit(
                        handle.prompt, handle.max_new_tokens,
                        deadline=handle.deadline, tenant=handle.tenant,
                        adapter_id=handle.adapter_id,
                        trace_id=handle.trace_id)
            except ValueError as e:      # oversized / empty prompt /
                self._finish_handle(handle, e)  # mismatched KV geometry
                continue
            self._live.append(handle)
        self._space.set()

    def shed(self, n=1):
        """Flag up to `n` live decodes for extraction at the next step
        boundary (load shedding, disaggregated serving): each victim's
        stream ends with `RequestMigrated(ticket)` and the router
        re-places it on a lighter replica. Victims are the decodes with
        the MOST remaining work (max_new_tokens - generated), so one
        migration sheds the most future load; requests that have not
        produced a token yet are skipped (nothing to hand off
        mid-stream — they are cheaper to let finish prefill first).
        Returns how many were flagged."""
        cands = [h for h in self._live
                 if not h.terminal and not h.cancel_requested
                 and not h.extract_requested and h.req is not None
                 and h.req.state == "decode" and h.req.output]
        cands.sort(key=lambda h: (
            -(h.req.max_new_tokens - len(h.req.output)),
            h.req.arrival))
        picked = cands[:int(n)]
        for h in picked:
            h.extract_requested = True
        if picked:
            self._wake.set()
        return len(picked)

    def _apply_extractions(self):
        """Extract shed-flagged decodes (between steps, loop thread —
        the same engine-mutation discipline as cancellation). Tokens
        generated before the flag were published by the previous
        `_publish`, so the migration sentinel is strictly ordered
        after every delivered token."""
        for handle in list(self._live):
            if not handle.extract_requested or handle.terminal:
                continue
            req = handle.req
            if req is None or req.state != "decode" or not req.output:
                continue                 # not extractable (yet)
            self._live.remove(handle)
            ticket = self.engine.extract_request(req)
            self._finish_handle(handle, RequestMigrated(ticket))

    def _stream_blocks(self):
        """Ship newly completed prefill blocks for handoff-destined
        requests (runs right after each step, before `_publish`, so
        the extraction tail stays minimal)."""
        for handle in self._live:
            if handle.on_blocks is None or handle.terminal:
                continue
            req = handle.req
            if req is None or req.slot < 0 or req.state != "prefill":
                continue
            chunk = self.engine.export_unshipped(req)
            if chunk is not None:
                handle.on_blocks(chunk)

    def _publish(self):
        """Push newly generated tokens + terminal states to waiters.
        On a prefill-role engine, requests that reached the "handoff"
        state (first token sampled) are extracted HERE — their stream
        delivers the token(s) first, then `RequestMigrated(ticket)`."""
        for handle in list(self._live):
            req = handle.req
            n = len(req.output)
            if n > handle.published:
                for tok in req.output[handle.published:n]:
                    handle.queue.put_nowait(tok)
                handle.published = n
            if req.done:
                self._live.remove(handle)
                if req.state == "finished":
                    self._finish_handle(handle, _DONE)
                elif req.state == "expired":
                    self._finish_handle(handle, DeadlineExceeded())
                else:
                    self._finish_handle(handle, RequestCancelled())
            elif req.state == "handoff":
                self._live.remove(handle)
                ticket = self.engine.extract_request(req)
                self._finish_handle(handle, RequestMigrated(ticket))

    def _next_pending_deadline(self):
        # handles waiting in the frontend queue never reach the
        # scheduler's expiry sweep, so the idle wait must wake for them
        soonest = None
        for h in self._fair.items():
            if h.deadline is not None and \
                    (soonest is None or h.deadline < soonest):
                soonest = h.deadline
        return soonest

    async def _step_loop(self):
        try:
            await self._step_loop_inner()
        except Exception as e:  # noqa: BLE001 — step/engine failure
            # a dying step loop must not strand awaiting callers on
            # queues nobody will ever fill: fail every handle with the
            # error and close the frontend
            self._closed = True
            self._space.set()
            while True:
                handle = self._fair.pop()
                if handle is None:
                    break
                self._finish_handle(handle, e)
            for handle in list(self._live):
                self._finish_handle(handle, e)
            self._live.clear()

    async def _step_loop_inner(self):
        loop = asyncio.get_running_loop()
        backoff = 0.0
        while not self._closed:
            self._apply_cancellations()
            self._apply_extractions()
            self._admit_pending()
            if self.engine.scheduler.has_work:
                self.step_calls += 1
                did = await loop.run_in_executor(None, self.engine.step)
                self._stream_blocks()
                self._publish()
                if did:
                    backoff = 0.0
                elif self.engine.scheduler.has_work:
                    # engine stall: the block pool cannot cover the
                    # resident working set (ServingEngine.run raises
                    # here) — fail the affected requests rather than
                    # spin
                    err = RuntimeError(
                        "serving engine stalled: KV block pool too "
                        "small for the resident working set")
                    for handle in list(self._live):
                        self.engine.cancel(handle.req)
                        self._live.remove(handle)
                        self._finish_handle(handle, err)
                else:
                    # unproductive round (no tokens, no expiries, and
                    # the work drained between the check and the step):
                    # back off instead of spinning the executor
                    backoff = min(backoff * 2 or self.IDLE_BACKOFF_S,
                                  self.IDLE_BACKOFF_MAX_S)
                    await asyncio.sleep(backoff)
                continue
            # idle: the engine has no work, which means _admit_pending
            # drained the fair queue (engine FIFO empty => depth free),
            # so sleep until a submission or cancel wakes us — or the
            # soonest frontend-held deadline passes (those handles
            # never reach the scheduler's expiry sweep). A multi-tick
            # engine may still hold the last dispatch's deferred
            # metrics/flight record — publish before sleeping so
            # scrapes during idle see the drained totals.
            flush = getattr(self.engine, "flush_observability", None)
            if flush is not None:
                flush()
            self._wake.clear()
            soonest = self._next_pending_deadline()
            try:
                if soonest is not None:
                    delay = max(0.0, soonest - self.engine.clock())
                    await asyncio.wait_for(self._wake.wait(), delay)
                else:
                    await self._wake.wait()
            except asyncio.TimeoutError:
                pass
