"""Block-paged KV cache for continuous batching.

The fixed-shape backbone of the serving engine (the TPU translation of
vLLM-style PagedAttention, per the "Ragged Paged Attention" shape
discipline): one `[L, num_blocks, block_size, H, Dh]` pool per K and V
covers EVERY request; a request owns an ordered list of blocks and the
per-slot block table is padded to a fixed `max_blocks_per_slot` width,
so the compiled mixed step sees identical shapes no matter which
requests are resident.

Block 0 is reserved as the NULL block: padding entries in block tables
and the cache writes of padding tokens all land there, and the
attention mask (`key position <= query position`) guarantees it is
never read through. The allocator hands out blocks `1..num_blocks-1`
LIFO so tests can observe free-list reuse directly.
"""
from __future__ import annotations

import numpy as np

NULL_BLOCK = 0


class BlockAllocator:
    """LIFO free-list over block ids [reserved, num_blocks), with
    per-block reference counts so the prefix cache can SHARE a block
    between several slot tables (and its own radix tree): `alloc` hands
    a block out at refcount 1, `incref` adds an owner, and `free`
    decrements — the block returns to the free list only when its last
    owner lets go. Allocation is still all-or-nothing."""

    def __init__(self, num_blocks, reserved=1):
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks={num_blocks} leaves no allocatable blocks "
                f"past the {reserved} reserved null block(s)")
        self.num_blocks = int(num_blocks)
        self.reserved = int(reserved)
        self._free = list(range(self.num_blocks - 1,
                                self.reserved - 1, -1))
        self._refs = {}                      # block id -> owner count

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return len(self._refs)

    @property
    def capacity(self):
        return self.num_blocks - self.reserved

    def refcount(self, block):
        return self._refs.get(block, 0)

    @property
    def invariant_ok(self):
        """allocated + free + reserved == pool size, with no overlap —
        the ledger the prefix-cache meta-test asserts after random
        alloc/share/CoW/truncate/free sequences."""
        allocated = set(self._refs)
        free = set(self._free)
        return (not (allocated & free)
                and len(self._free) == len(free)
                and len(allocated) + len(free) + self.reserved
                == self.num_blocks
                and all(c > 0 for c in self._refs.values()))

    def alloc(self, n):
        """n blocks (each at refcount 1), or None when the pool can't
        cover the request — the caller decides whether to preempt
        (never partial)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def incref(self, blocks):
        """Add an owner to already-allocated blocks (prefix sharing)."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"incref of unallocated block {b}")
            self._refs[b] += 1

    def free(self, blocks):
        """Drop one owner per block; a block whose count hits zero goes
        back on the free list."""
        for b in blocks:
            c = self._refs.get(b, 0)
            if c <= 0:
                raise ValueError(f"double free of block {b}")
            if c == 1:
                del self._refs[b]
                self._free.append(b)
            else:
                self._refs[b] = c - 1


class PagedKVCache:
    """Paged pools + per-slot block tables + the slot length ledger.

    `kv_dtype="int8"` stores the pools quantized: int8 payloads plus
    fp32 scale pools `k_scale`/`v_scale` of shape `[L, NB, BS, H]` —
    one scale per pool ENTRY per head, riding exactly the same
    `(block, offset)` coordinates as the K/V bytes, so every consumer
    of a block id (slot tables, CoW, truncate, prefix-cache adoption)
    carries the scales for free. The granularity is deliberately
    per-entry rather than per-whole-block: blocks fill incrementally
    across steps and are SHARED between requests (radix prefix cache),
    so a whole-block scale would make already-written int8 values
    depend on later appends — per-entry scales keep quantization a
    pure function of the token's own fp K/V, which is what preserves
    the prefix-cache contract ("cached K/V is exactly what
    re-prefilling would write") and makes the int8 engine
    deterministic under chunking, preemption and sharing."""

    def __init__(self, num_layers, num_heads, head_dim, *, num_blocks,
                 block_size, max_slots, max_blocks_per_slot,
                 dtype="float32", kv_dtype=None):
        import jax.numpy as jnp
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.dtype = str(dtype)
        self.kv_dtype = str(kv_dtype) if kv_dtype else self.dtype
        if self.kv_dtype not in ("float32", "bfloat16", "float16",
                                 "int8"):
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} not supported; use a float "
                "dtype or 'int8' (per-entry-per-head scaled)")
        shape = (num_layers, self.num_blocks, self.block_size,
                 num_heads, head_dim)
        self.k_pool = jnp.zeros(shape, jnp.dtype(self.kv_dtype))
        self.v_pool = jnp.zeros(shape, jnp.dtype(self.kv_dtype))
        self.k_scale = self.v_scale = None
        if self.quantized:
            sshape = shape[:-1]                      # [L, NB, BS, H]
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
        self.allocator = BlockAllocator(self.num_blocks)
        self.block_tables = np.zeros(
            (self.max_slots, self.max_blocks_per_slot), np.int32)
        self._slot_blocks = [[] for _ in range(self.max_slots)]
        self.slot_lens = np.zeros(self.max_slots, np.int32)
        # optional radix prefix cache (serving.prefix_cache): when the
        # free list runs dry, refcount-0 cached leaves are evicted
        # before an allocation is refused
        self.prefix_cache = None
        self._copy_fn = None

    # ------------------------------------------------------------ sizing
    @property
    def quantized(self):
        return self.kv_dtype == "int8"

    @property
    def kv_bytes_per_token(self):
        """HBM bytes one cached token costs across K+V and all layers,
        including the quantization scales — the number the
        `paddle_tpu_serving_kv_bytes_per_token` gauge publishes and
        `tools/kv_smoke.py` budgets with. Read per engine step for the
        gauge, so it is pure host arithmetic on fixed geometry (the
        explicit itemsize map mirrors the kv_dtype whitelist in
        __init__ — np.dtype only knows "bfloat16" after jax registers
        ml_dtypes, an import-order dependency not worth having)."""
        itemsize = {"float32": 4, "bfloat16": 2,
                    "float16": 2, "int8": 1}[self.kv_dtype]
        per = self.num_heads * self.head_dim * itemsize
        if self.quantized:
            per += self.num_heads * 4            # fp32 scale per head
        return 2 * self.num_layers * per         # K and V

    @property
    def block_bytes(self):
        """HBM bytes one K+V block (all layers) occupies, incl scales."""
        return self.kv_bytes_per_token * self.block_size

    @property
    def max_slot_tokens(self):
        return self.max_blocks_per_slot * self.block_size

    def blocks_for(self, n_tokens):
        return -(-int(n_tokens) // self.block_size)

    def blocks_missing(self, slot, new_len):
        return max(0, self.blocks_for(new_len)
                   - len(self._slot_blocks[slot]))

    def slot_num_blocks(self, slot):
        return len(self._slot_blocks[slot])

    def slot_blocks(self, slot):
        """The slot's ordered block list (a copy)."""
        return list(self._slot_blocks[slot])

    # --------------------------------------------------------- lifecycle
    def _alloc(self, n):
        """Allocator alloc with the prefix-cache backstop: a dry free
        list first evicts LRU refcount-0 cached leaves, then retries —
        so cached-but-idle blocks never cause a preemption the pool
        could have absorbed."""
        got = self.allocator.alloc(n)
        if got is None and self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.allocator.num_free)
            got = self.allocator.alloc(n)
        return got

    def ensure_capacity(self, slot, new_len) -> bool:
        """Grow `slot`'s block table to cover `new_len` tokens. False
        (state unchanged) when the free list can't supply the blocks."""
        if new_len > self.max_slot_tokens:
            raise ValueError(
                f"slot needs {new_len} tokens but max_blocks_per_slot="
                f"{self.max_blocks_per_slot} x block_size="
                f"{self.block_size} caps it at {self.max_slot_tokens}")
        need = self.blocks_missing(slot, new_len)
        if need == 0:
            return True
        got = self._alloc(need)
        if got is None:
            return False
        row = self._slot_blocks[slot]
        for b in got:
            self.block_tables[slot, len(row)] = b
            row.append(b)
        return True

    # ---------------------------------------------------- prefix sharing
    def adopt_blocks(self, slot, blocks):
        """Append already-allocated (cached) blocks to `slot`'s table,
        taking one reference per block. Used at admission when the
        prefix cache matched the head of the prompt — the slot reads
        these blocks but never writes them (its first uncached token
        lands in the next, privately-allocated block)."""
        row = self._slot_blocks[slot]
        if len(row) + len(blocks) > self.max_blocks_per_slot:
            raise ValueError("adopted prefix exceeds max_blocks_per_slot")
        self.allocator.incref(blocks)
        for b in blocks:
            self.block_tables[slot, len(row)] = b
            row.append(b)

    def cow_block(self, slot, index):
        """Copy-on-write `slot`'s table entry at `index`: allocate a
        private block, device-copy the shared block's K/V columns into
        it, swap the table entry and drop the slot's reference on the
        original. Returns True on success, False (state unchanged) when
        no block could be allocated even after cache eviction.

        This is how a request EXTENDS a shared block: the matched
        prefix may end mid-block (e.g. the prompt's last token falls
        inside a fully-cached block, and the last prompt token must
        always be re-fed to sample the first output). Writing there
        would corrupt every other reader, so the writer gets its own
        copy first."""
        row = self._slot_blocks[slot]
        src = row[index]
        got = self._alloc(1)
        if got is None:
            return False
        dst = got[0]
        self._copy_block_data(src, dst)
        row[index] = dst
        self.block_tables[slot, index] = dst
        self.allocator.free([src])
        return True

    def _copy_block_data(self, src, dst):
        """pool[:, dst] = pool[:, src] for K and V, as ONE jitted
        fixed-shape copy (block ids ride as traced scalars, so every
        CoW reuses the same executable; pools are donated in place).
        Quantized pools copy the per-entry scale columns in the SAME
        executable — a CoW'd block dequantizes identically to its
        source."""
        import jax.numpy as jnp

        if self._copy_fn is None:
            from ..jit.functional import instrumented_jit

            if self.quantized:
                def copy(kp, vp, ks, vs, src, dst):
                    return (kp.at[:, dst].set(kp[:, src]),
                            vp.at[:, dst].set(vp[:, src]),
                            ks.at[:, dst].set(ks[:, src]),
                            vs.at[:, dst].set(vs[:, src]))

                self._copy_fn = instrumented_jit(
                    copy, "serving_prefix_cow",
                    donate_argnums=(0, 1, 2, 3))
            else:
                def copy(kp, vp, src, dst):
                    return (kp.at[:, dst].set(kp[:, src]),
                            vp.at[:, dst].set(vp[:, src]))

                self._copy_fn = instrumented_jit(
                    copy, "serving_prefix_cow", donate_argnums=(0, 1))
        if self.quantized:
            (self.k_pool, self.v_pool, self.k_scale,
             self.v_scale) = self._copy_fn(
                self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                jnp.int32(src), jnp.int32(dst))
        else:
            self.k_pool, self.v_pool = self._copy_fn(
                self.k_pool, self.v_pool, jnp.int32(src), jnp.int32(dst))

    def truncate_slot(self, slot, new_len):
        """Roll back `slot` to cover only `new_len` tokens: blocks past
        `blocks_for(new_len)` go back to the free list and their table
        entries reset to NULL. Returns the number of blocks freed.

        This is the speculative-decode rollback: rejected draft tokens
        may have forced block allocations their K/V never ended up
        needing; the garbage they DID write into still-owned blocks
        needs no cleanup (the position mask hides it and the next
        accepted tokens overwrite it)."""
        keep = self.blocks_for(new_len)
        row = self._slot_blocks[slot]
        if len(row) <= keep:
            return 0
        extra = row[keep:]
        self.allocator.free(extra)
        self._slot_blocks[slot] = row[:keep]
        self.block_tables[slot, keep:] = NULL_BLOCK
        return len(extra)

    def release_slot(self, slot):
        row = self._slot_blocks[slot]
        if row:
            self.allocator.free(row)
        self._slot_blocks[slot] = []
        self.block_tables[slot, :] = NULL_BLOCK
        self.slot_lens[slot] = 0

    # ----------------------------------------------------------- metrics
    @property
    def blocks_in_use(self):
        return self.allocator.num_used

    @property
    def utilization(self):
        return self.allocator.num_used / max(1, self.allocator.capacity)
