"""Block-paged KV cache for continuous batching.

The fixed-shape backbone of the serving engine (the TPU translation of
vLLM-style PagedAttention, per the "Ragged Paged Attention" shape
discipline): one `[L, num_blocks, block_size, H, Dh]` pool per K and V
covers EVERY request; a request owns an ordered list of blocks and the
per-slot block table is padded to a fixed `max_blocks_per_slot` width,
so the compiled mixed step sees identical shapes no matter which
requests are resident.

Block 0 is reserved as the NULL block: padding entries in block tables
and the cache writes of padding tokens all land there, and the
attention mask (`key position <= query position`) guarantees it is
never read through. The allocator hands out blocks `1..num_blocks-1`
LIFO so tests can observe free-list reuse directly.
"""
from __future__ import annotations

import numpy as np

NULL_BLOCK = 0


class BlockAllocator:
    """LIFO free-list over block ids [reserved, num_blocks)."""

    def __init__(self, num_blocks, reserved=1):
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks={num_blocks} leaves no allocatable blocks "
                f"past the {reserved} reserved null block(s)")
        self.num_blocks = int(num_blocks)
        self.reserved = int(reserved)
        self._free = list(range(self.num_blocks - 1,
                                self.reserved - 1, -1))
        self._used = set()

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return len(self._used)

    @property
    def capacity(self):
        return self.num_blocks - self.reserved

    def alloc(self, n):
        """n blocks, or None when the pool can't cover the request —
        the caller decides whether to preempt (never partial)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._used.update(out)
        return out

    def free(self, blocks):
        for b in blocks:
            if b not in self._used:
                raise ValueError(f"double free of block {b}")
            self._used.remove(b)
            self._free.append(b)


class PagedKVCache:
    """Paged pools + per-slot block tables + the slot length ledger."""

    def __init__(self, num_layers, num_heads, head_dim, *, num_blocks,
                 block_size, max_slots, max_blocks_per_slot,
                 dtype="float32"):
        import jax.numpy as jnp
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.dtype = str(dtype)
        shape = (num_layers, self.num_blocks, self.block_size,
                 num_heads, head_dim)
        self.k_pool = jnp.zeros(shape, jnp.dtype(self.dtype))
        self.v_pool = jnp.zeros(shape, jnp.dtype(self.dtype))
        self.allocator = BlockAllocator(self.num_blocks)
        self.block_tables = np.zeros(
            (self.max_slots, self.max_blocks_per_slot), np.int32)
        self._slot_blocks = [[] for _ in range(self.max_slots)]
        self.slot_lens = np.zeros(self.max_slots, np.int32)

    # ------------------------------------------------------------ sizing
    @property
    def max_slot_tokens(self):
        return self.max_blocks_per_slot * self.block_size

    def blocks_for(self, n_tokens):
        return -(-int(n_tokens) // self.block_size)

    def blocks_missing(self, slot, new_len):
        return max(0, self.blocks_for(new_len)
                   - len(self._slot_blocks[slot]))

    def slot_num_blocks(self, slot):
        return len(self._slot_blocks[slot])

    # --------------------------------------------------------- lifecycle
    def ensure_capacity(self, slot, new_len) -> bool:
        """Grow `slot`'s block table to cover `new_len` tokens. False
        (state unchanged) when the free list can't supply the blocks."""
        if new_len > self.max_slot_tokens:
            raise ValueError(
                f"slot needs {new_len} tokens but max_blocks_per_slot="
                f"{self.max_blocks_per_slot} x block_size="
                f"{self.block_size} caps it at {self.max_slot_tokens}")
        need = self.blocks_missing(slot, new_len)
        if need == 0:
            return True
        got = self.allocator.alloc(need)
        if got is None:
            return False
        row = self._slot_blocks[slot]
        for b in got:
            self.block_tables[slot, len(row)] = b
            row.append(b)
        return True

    def truncate_slot(self, slot, new_len):
        """Roll back `slot` to cover only `new_len` tokens: blocks past
        `blocks_for(new_len)` go back to the free list and their table
        entries reset to NULL. Returns the number of blocks freed.

        This is the speculative-decode rollback: rejected draft tokens
        may have forced block allocations their K/V never ended up
        needing; the garbage they DID write into still-owned blocks
        needs no cleanup (the position mask hides it and the next
        accepted tokens overwrite it)."""
        keep = self.blocks_for(new_len)
        row = self._slot_blocks[slot]
        if len(row) <= keep:
            return 0
        extra = row[keep:]
        self.allocator.free(extra)
        self._slot_blocks[slot] = row[:keep]
        self.block_tables[slot, keep:] = NULL_BLOCK
        return len(extra)

    def release_slot(self, slot):
        row = self._slot_blocks[slot]
        if row:
            self.allocator.free(row)
        self._slot_blocks[slot] = []
        self.block_tables[slot, :] = NULL_BLOCK
        self.slot_lens[slot] = 0

    # ----------------------------------------------------------- metrics
    @property
    def blocks_in_use(self):
        return self.allocator.num_used

    @property
    def utilization(self):
        return self.allocator.num_used / max(1, self.allocator.capacity)
