"""Block-paged KV cache for continuous batching.

The fixed-shape backbone of the serving engine (the TPU translation of
vLLM-style PagedAttention, per the "Ragged Paged Attention" shape
discipline): one `[L, num_blocks, block_size, H, Dh]` pool per K and V
covers EVERY request; a request owns an ordered list of blocks and the
per-slot block table is padded to a fixed `max_blocks_per_slot` width,
so the compiled mixed step sees identical shapes no matter which
requests are resident.

Block 0 is reserved as the NULL block: padding entries in block tables
and the cache writes of padding tokens all land there, and the
attention mask (`key position <= query position`) guarantees it is
never read through. The allocator hands out blocks `1..num_blocks-1`
LIFO so tests can observe free-list reuse directly.
"""
from __future__ import annotations

import numpy as np

NULL_BLOCK = 0

#: supported pool dtypes -> (HBM bytes per element, whether the pool
#: stores quantized payloads needing per-entry-per-head fp32 scales).
#: THE one list `Config(kv_dtype=)` and the constructor validate
#: against — an unknown dtype fails here with the supported set in
#: the message, never as a deep KeyError in the sizing math.
KV_DTYPES = {
    "float32": (4, False),
    "bfloat16": (2, False),
    "float16": (2, False),
    "int8": (1, True),
    # fp8 KV pools (ISSUE 15): e4m3 payloads under the SAME per-entry
    # per-head fp32 scale plumbing as int8 — quantize-on-append scales
    # amax to the e4m3 max (448) so the full mantissa range is used
    # per entry; CPU-testable via ml_dtypes
    "fp8_e4m3": (1, True),
}

#: fp8 format constants (ml_dtypes float8_e4m3fn): finite max 448;
#: values past it cast to NaN, so quantize clips first
FP8_MAX = 448.0

#: "empty" sentinel for the min summary rows (max rows use the
#: negation): large but finite — far above any real key magnitude, far
#: enough below float32 max that score products stay finite — so a
#: never-written row scores a huge NEGATIVE upper bound (never
#: selected) without NaN-ing the scorer's arithmetic the way +/-inf
#: would
SUMMARY_INIT = 1e30


def kv_jnp_dtype(kv_dtype):
    """The jnp storage dtype for a `KV_DTYPES` name ("fp8_e4m3" is a
    serving-facing alias of ml_dtypes' float8_e4m3fn)."""
    import jax.numpy as jnp
    if kv_dtype == "fp8_e4m3":
        return jnp.float8_e4m3fn
    return jnp.dtype(kv_dtype)


class BlockAllocator:
    """LIFO free-list over block ids [reserved, num_blocks), with
    per-block reference counts so the prefix cache can SHARE a block
    between several slot tables (and its own radix tree): `alloc` hands
    a block out at refcount 1, `incref` adds an owner, and `free`
    decrements — the block returns to the free list only when its last
    owner lets go. Allocation is still all-or-nothing."""

    def __init__(self, num_blocks, reserved=1):
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks={num_blocks} leaves no allocatable blocks "
                f"past the {reserved} reserved null block(s)")
        self.num_blocks = int(num_blocks)
        self.reserved = int(reserved)
        self._free = list(range(self.num_blocks - 1,
                                self.reserved - 1, -1))
        self._refs = {}                      # block id -> owner count

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_used(self):
        return len(self._refs)

    @property
    def capacity(self):
        return self.num_blocks - self.reserved

    def refcount(self, block):
        return self._refs.get(block, 0)

    @property
    def invariant_ok(self):
        """allocated + free + reserved == pool size, with no overlap —
        the ledger the prefix-cache meta-test asserts after random
        alloc/share/CoW/truncate/free sequences."""
        allocated = set(self._refs)
        free = set(self._free)
        return (not (allocated & free)
                and len(self._free) == len(free)
                and len(allocated) + len(free) + self.reserved
                == self.num_blocks
                and all(c > 0 for c in self._refs.values()))

    def alloc(self, n):
        """n blocks (each at refcount 1), or None when the pool can't
        cover the request — the caller decides whether to preempt
        (never partial)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def incref(self, blocks):
        """Add an owner to already-allocated blocks (prefix sharing)."""
        for b in blocks:
            if b not in self._refs:
                raise ValueError(f"incref of unallocated block {b}")
            self._refs[b] += 1

    def free(self, blocks):
        """Drop one owner per block; a block whose count hits zero goes
        back on the free list."""
        for b in blocks:
            c = self._refs.get(b, 0)
            if c <= 0:
                raise ValueError(f"double free of block {b}")
            if c == 1:
                del self._refs[b]
                self._free.append(b)
            else:
                self._refs[b] = c - 1


class PagedKVCache:
    """Paged pools + per-slot block tables + the slot length ledger.

    `kv_dtype="int8"` stores the pools quantized: int8 payloads plus
    fp32 scale pools `k_scale`/`v_scale` of shape `[L, NB, BS, H]` —
    one scale per pool ENTRY per head, riding exactly the same
    `(block, offset)` coordinates as the K/V bytes, so every consumer
    of a block id (slot tables, CoW, truncate, prefix-cache adoption)
    carries the scales for free. The granularity is deliberately
    per-entry rather than per-whole-block: blocks fill incrementally
    across steps and are SHARED between requests (radix prefix cache),
    so a whole-block scale would make already-written int8 values
    depend on later appends — per-entry scales keep quantization a
    pure function of the token's own fp K/V, which is what preserves
    the prefix-cache contract ("cached K/V is exactly what
    re-prefilling would write") and makes the int8 engine
    deterministic under chunking, preemption and sharing.

    `kv_dtype="fp8_e4m3"` rides the exact same plumbing with e4m3
    payloads (ml_dtypes), halving KV bytes again vs the int8 story's
    fp32 baseline and composing with sparsity, TP sharding, transport
    and the prefix cache for free.

    `summaries=True` (the block-sparse attention substrate, ISSUE 15)
    additionally keeps per-(pool-block, head) CHANNEL-WISE min/max
    key summaries `k_sum_min`/`k_sum_max` `[L, NB, H, Dh]` fp32,
    updated on append inside the jitted mixed step (the offset-0
    write of a block RESETS its row, so freed-then-reused blocks can
    never leak a previous owner's statistics). Summary rows ride the
    same block coordinates as the scale rows, so CoW, truncation,
    prefix adoption and migration transport carry them by
    construction."""

    def __init__(self, num_layers, num_heads, head_dim, *, num_blocks,
                 block_size, max_slots, max_blocks_per_slot,
                 dtype="float32", kv_dtype=None, summaries=False):
        import jax.numpy as jnp
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        self.dtype = str(dtype)
        self.kv_dtype = str(kv_dtype) if kv_dtype else self.dtype
        if self.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype={self.kv_dtype!r} not supported; pick one "
                f"of {sorted(KV_DTYPES)} ('int8'/'fp8_e4m3' store "
                "per-entry-per-head scaled quantized pools)")
        self.summaries = bool(summaries)
        shape = (num_layers, self.num_blocks, self.block_size,
                 num_heads, head_dim)
        self.k_pool = jnp.zeros(shape, kv_jnp_dtype(self.kv_dtype))
        self.v_pool = jnp.zeros(shape, kv_jnp_dtype(self.kv_dtype))
        self.k_scale = self.v_scale = None
        if self.quantized:
            sshape = shape[:-1]                      # [L, NB, BS, H]
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
        self.k_sum_min = self.k_sum_max = None
        if self.summaries:
            # min starts high / max starts low so the first append of
            # a block's offset-0 entry (which resets the row anyway)
            # and an unwritten row alike can never look attractive to
            # the block scorer
            mshape = (num_layers, self.num_blocks, num_heads, head_dim)
            self.k_sum_min = jnp.full(mshape, SUMMARY_INIT, jnp.float32)
            self.k_sum_max = jnp.full(mshape, -SUMMARY_INIT,
                                      jnp.float32)
        self.allocator = BlockAllocator(self.num_blocks)
        self.block_tables = np.zeros(
            (self.max_slots, self.max_blocks_per_slot), np.int32)
        self._slot_blocks = [[] for _ in range(self.max_slots)]
        self.slot_lens = np.zeros(self.max_slots, np.int32)
        # optional radix prefix cache (serving.prefix_cache): when the
        # free list runs dry, refcount-0 cached leaves are evicted
        # before an allocation is refused
        self.prefix_cache = None
        self._copy_fn = None
        # block transport (serving.distributed.transport): jitted
        # gather/scatter executables per pow2 id-width, raw transfer
        # counters (the engine mirrors them into the metrics registry),
        # and an optional re-placement hook a sharded engine installs
        # so imported pools return to their canonical mesh sharding
        # (a spec drift here would silently recompile the mixed step)
        self._transfer_fns = {}
        self.place_pools = None
        self.blocks_exported = 0
        self.blocks_imported = 0

    # ------------------------------------------------------------ sizing
    @property
    def quantized(self):
        return KV_DTYPES[self.kv_dtype][1]

    @property
    def kv_bytes_per_token(self):
        """HBM bytes one cached token costs across K+V and all layers,
        including the quantization scales and (amortized per token)
        the block-summary rows — the number the
        `paddle_tpu_serving_kv_bytes_per_token` gauge publishes and
        `tools/kv_smoke.py`/`tools/longctx_smoke.py` budget with. Read
        per engine step for the gauge, so it is pure host arithmetic
        on fixed geometry (the explicit `KV_DTYPES` itemsize map —
        np.dtype only knows "bfloat16"/fp8 after jax registers
        ml_dtypes, an import-order dependency not worth having)."""
        itemsize = KV_DTYPES[self.kv_dtype][0]
        per = self.num_heads * self.head_dim * itemsize
        if self.quantized:
            per += self.num_heads * 4            # fp32 scale per head
        per *= 2                                 # K and V
        if self.summaries:
            # one fp32 min + max K-summary row per BLOCK, spread over
            # its block_size tokens (K only — the scorer never needs V)
            per += (2 * self.num_heads * self.head_dim * 4
                    ) // self.block_size
        return self.num_layers * per

    @property
    def block_bytes(self):
        """HBM bytes one K+V block (all layers) occupies, incl scales."""
        return self.kv_bytes_per_token * self.block_size

    @property
    def max_slot_tokens(self):
        return self.max_blocks_per_slot * self.block_size

    def blocks_for(self, n_tokens):
        return -(-int(n_tokens) // self.block_size)

    def blocks_missing(self, slot, new_len):
        return max(0, self.blocks_for(new_len)
                   - len(self._slot_blocks[slot]))

    def slot_num_blocks(self, slot):
        return len(self._slot_blocks[slot])

    def slot_blocks(self, slot):
        """The slot's ordered block list (a copy)."""
        return list(self._slot_blocks[slot])

    # --------------------------------------------------------- lifecycle
    def _alloc(self, n):
        """Allocator alloc with the prefix-cache backstop: a dry free
        list first evicts LRU refcount-0 cached leaves, then retries —
        so cached-but-idle blocks never cause a preemption the pool
        could have absorbed."""
        got = self.allocator.alloc(n)
        if got is None and self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.allocator.num_free)
            got = self.allocator.alloc(n)
        return got

    def ensure_capacity(self, slot, new_len) -> bool:
        """Grow `slot`'s block table to cover `new_len` tokens. False
        (state unchanged) when the free list can't supply the blocks."""
        if new_len > self.max_slot_tokens:
            raise ValueError(
                f"slot needs {new_len} tokens but max_blocks_per_slot="
                f"{self.max_blocks_per_slot} x block_size="
                f"{self.block_size} caps it at {self.max_slot_tokens}")
        need = self.blocks_missing(slot, new_len)
        if need == 0:
            return True
        got = self._alloc(need)
        if got is None:
            return False
        row = self._slot_blocks[slot]
        for b in got:
            self.block_tables[slot, len(row)] = b
            row.append(b)
        return True

    # ---------------------------------------------------- prefix sharing
    def adopt_blocks(self, slot, blocks):
        """Append already-allocated (cached) blocks to `slot`'s table,
        taking one reference per block. Used at admission when the
        prefix cache matched the head of the prompt — the slot reads
        these blocks but never writes them (its first uncached token
        lands in the next, privately-allocated block)."""
        row = self._slot_blocks[slot]
        if len(row) + len(blocks) > self.max_blocks_per_slot:
            raise ValueError("adopted prefix exceeds max_blocks_per_slot")
        self.allocator.incref(blocks)
        for b in blocks:
            self.block_tables[slot, len(row)] = b
            row.append(b)

    def cow_block(self, slot, index):
        """Copy-on-write `slot`'s table entry at `index`: allocate a
        private block, device-copy the shared block's K/V columns into
        it, swap the table entry and drop the slot's reference on the
        original. Returns True on success, False (state unchanged) when
        no block could be allocated even after cache eviction.

        This is how a request EXTENDS a shared block: the matched
        prefix may end mid-block (e.g. the prompt's last token falls
        inside a fully-cached block, and the last prompt token must
        always be re-fed to sample the first output). Writing there
        would corrupt every other reader, so the writer gets its own
        copy first."""
        row = self._slot_blocks[slot]
        src = row[index]
        got = self._alloc(1)
        if got is None:
            return False
        dst = got[0]
        self._copy_block_data(src, dst)
        row[index] = dst
        self.block_tables[slot, index] = dst
        self.allocator.free([src])
        return True

    def _copy_block_data(self, src, dst):
        """pool[:, dst] = pool[:, src] for every pool array, as ONE
        jitted fixed-shape copy (block ids ride as traced scalars, so
        every CoW reuses the same executable; pools are donated in
        place). Quantized pools copy the per-entry scale columns and
        summary-tracking pools the block-summary rows in the SAME
        executable — every array indexes its block at axis 1, so a
        CoW'd block dequantizes AND scores identically to its
        source."""
        import jax.numpy as jnp

        if self._copy_fn is None:
            from ..jit.functional import instrumented_jit
            n = len(self._pools())

            def copy(*args):
                pools, src, dst = args[:n], args[n], args[n + 1]
                return tuple(p.at[:, dst].set(p[:, src]) for p in pools)

            self._copy_fn = instrumented_jit(
                copy, "serving_prefix_cow",
                donate_argnums=tuple(range(n)))
        out = self._copy_fn(*self._pools(), jnp.int32(src),
                            jnp.int32(dst))
        self._set_pools(out)

    # ------------------------------------------------- block transport
    def kv_meta(self):
        """The pool geometry a KV transfer must agree on end to end —
        shipped in every codec frame so a mismatched fleet is refused
        at import instead of corrupting a pool."""
        return {"num_layers": self.num_layers,
                "num_heads": self.num_heads,
                "head_dim": self.head_dim,
                "block_size": self.block_size,
                "dtype": self.dtype,
                "kv_dtype": self.kv_dtype,
                "summaries": self.summaries}

    def _transfer_fn(self, kind, width):
        """Jitted gather ("export") / donated scatter ("import") over
        the pools for a `[width]` block-id vector. One instrumented
        instance per (kind, pow2 width): ids ride as traced values, so
        every transfer of up to `width` blocks reuses the same
        executable — no per-block (or per-count) compile. Every pool
        array (payloads, scales, summaries) indexes its block at axis
        1, so one generic gather/scatter covers them all."""
        fn = self._transfer_fns.get((kind, width))
        if fn is not None:
            return fn
        import jax.numpy as jnp

        from ..jit.functional import instrumented_jit
        n = len(self._pools())

        if kind == "export":
            def gather(*args):
                pools, ids = args[:n], args[n]
                return tuple(jnp.moveaxis(p[:, ids], 1, 0)
                             for p in pools)

            fn = instrumented_jit(gather, "serving_kv_export")
        elif kind == "import":
            def scatter(*args):
                pools, ids, payload = args[:n], args[n], args[n + 1:]
                return tuple(
                    p.at[:, ids].set(jnp.moveaxis(a, 0, 1))
                    for p, a in zip(pools, payload))

            fn = instrumented_jit(scatter, "serving_kv_import",
                                  donate_argnums=tuple(range(n)))
        else:
            raise ValueError(f"unknown transfer kind {kind!r}")
        self._transfer_fns[(kind, width)] = fn
        return fn

    def _pools(self):
        out = [self.k_pool, self.v_pool]
        if self.quantized:
            out += [self.k_scale, self.v_scale]
        if self.summaries:
            out += [self.k_sum_min, self.k_sum_max]
        return out

    def _set_pools(self, arrays):
        """Inverse of `_pools()`: rebind the pool attributes from a
        jitted executable's output tuple (same fixed order)."""
        arrays = list(arrays)
        self.k_pool, self.v_pool = arrays[:2]
        arrays = arrays[2:]
        if self.quantized:
            self.k_scale, self.v_scale = arrays[:2]
            arrays = arrays[2:]
        if self.summaries:
            self.k_sum_min, self.k_sum_max = arrays[:2]

    def export_blocks(self, block_ids):
        """Read `block_ids`' pool columns out to host arrays: a tuple
        `(k, v)` — plus `(k_scale, v_scale)` for quantized pools and
        `(k_sum_min, k_sum_max)` for summary-tracking ones — each
        `[n, L, ...]` (block-major, so one block's bytes are
        contiguous for the wire codec). One jitted fixed-shape gather
        per pow2 id-width; ids need not be contiguous or ordered. The
        scale and summary rows ride the same block coordinates by
        construction, so an exported block dequantizes AND scores
        identically wherever it lands."""
        import jax.numpy as jnp

        from .batcher import next_pow2
        ids = [int(b) for b in block_ids]
        if not ids:
            raise ValueError("export_blocks needs at least one block")
        n = len(ids)
        width = next_pow2(n, lo=1)
        padded = np.zeros(width, np.int32)     # pad with the NULL block
        padded[:n] = ids
        out = self._transfer_fn("export", width)(
            *self._pools(), jnp.asarray(padded))
        self.blocks_exported += n
        return tuple(np.asarray(a)[:n] for a in out)

    def import_blocks(self, block_ids, arrays):
        """Scatter transported block payloads into `block_ids` (already
        allocated by the caller): the donated-pool inverse of
        `export_blocks`, one jitted fixed-shape scatter per pow2
        id-width. Payload dtypes/shapes are validated against the pool
        geometry first — a mismatched fleet is refused, never written.
        Padding entries land in the reserved NULL block, which is never
        read through."""
        import jax.numpy as jnp

        from .batcher import next_pow2
        ids = [int(b) for b in block_ids]
        if not ids:
            raise ValueError("import_blocks needs at least one block")
        n = len(ids)
        pools = self._pools()
        if len(arrays) != len(pools):
            raise ValueError(
                f"expected {len(pools)} payload arrays for "
                f"kv_dtype={self.kv_dtype!r}, got {len(arrays)}")
        for a, p in zip(arrays, pools):
            expect = (n, p.shape[0]) + tuple(p.shape[2:])
            if tuple(a.shape) != expect or str(a.dtype) != str(p.dtype):
                raise ValueError(
                    f"payload {tuple(a.shape)}/{a.dtype} does not match "
                    f"pool geometry {expect}/{p.dtype}")
        width = next_pow2(n, lo=1)
        padded_ids = np.zeros(width, np.int32)
        padded_ids[:n] = ids
        payload = []
        for a in arrays:
            a = np.asarray(a)
            if width > n:
                a = np.concatenate(
                    [a, np.zeros((width - n,) + a.shape[1:], a.dtype)],
                    axis=0)
            payload.append(jnp.asarray(a))
        out = self._transfer_fn("import", width)(
            *pools, jnp.asarray(padded_ids), *payload)
        self._set_pools(out)
        self.blocks_imported += n
        if self.place_pools is not None:
            # sharded engines re-pin the canonical pool sharding so the
            # next mixed step's input specs are byte-identical (the
            # PR 8/PR 10 silent-recompile lesson)
            self.place_pools(self)

    def import_into_slot(self, slot, slot_len, chunks):
        """Admit a migrated request's KV: allocate destination blocks
        covering `slot_len` tokens, scatter the transported chunks into
        them, and wire up `slot`'s table. Chunk coverage is validated
        to be exactly blocks [0, blocks_for(slot_len)) with no gaps
        BEFORE any allocation. Returns False (state unchanged) when the
        free list — after the prefix-cache eviction backstop — cannot
        supply the blocks; the scheduler leaves the request queued and
        retries next plan."""
        if slot_len <= 0:
            raise ValueError(f"import_into_slot needs slot_len >= 1, "
                             f"got {slot_len}")
        need = self.blocks_for(slot_len)
        ordered = sorted(chunks, key=lambda c: c.start)
        at = 0
        for c in ordered:
            if c.start != at:
                raise ValueError(
                    f"migration chunks leave a gap at block {at} "
                    f"(next chunk starts at {c.start})")
            at += c.count
        if at != need:
            raise ValueError(
                f"migration chunks cover {at} blocks but slot_len="
                f"{slot_len} needs {need}")
        if self._slot_blocks[slot]:
            raise ValueError(f"slot {slot} is not empty")
        got = self._alloc(need)
        if got is None:
            return False
        try:
            for c in ordered:
                self.import_blocks(got[c.start:c.start + c.count],
                                   c.arrays)
        except Exception:
            self.allocator.free(got)
            raise
        self._slot_blocks[slot] = list(got)
        self.block_tables[slot, :need] = got
        self.block_tables[slot, need:] = NULL_BLOCK
        self.slot_lens[slot] = slot_len
        return True

    def truncate_slot(self, slot, new_len):
        """Roll back `slot` to cover only `new_len` tokens: blocks past
        `blocks_for(new_len)` go back to the free list and their table
        entries reset to NULL. Returns the number of blocks freed.

        This is the speculative-decode rollback: rejected draft tokens
        may have forced block allocations their K/V never ended up
        needing; the garbage they DID write into still-owned blocks
        needs no cleanup (the position mask hides it and the next
        accepted tokens overwrite it)."""
        keep = self.blocks_for(new_len)
        row = self._slot_blocks[slot]
        if len(row) <= keep:
            return 0
        extra = row[keep:]
        self.allocator.free(extra)
        self._slot_blocks[slot] = row[:keep]
        self.block_tables[slot, keep:] = NULL_BLOCK
        return len(extra)

    def release_slot(self, slot):
        row = self._slot_blocks[slot]
        if row:
            self.allocator.free(row)
        self._slot_blocks[slot] = []
        self.block_tables[slot, :] = NULL_BLOCK
        self.slot_lens[slot] = 0

    # ----------------------------------------------------------- metrics
    @property
    def blocks_in_use(self):
        return self.allocator.num_used

    @property
    def utilization(self):
        return self.allocator.num_used / max(1, self.allocator.capacity)
