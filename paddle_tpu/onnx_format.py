"""Minimal ONNX protobuf writer/reader (no `onnx` dependency).

The ONNX serialization is standard protobuf; this module hand-encodes
the subset of `onnx.proto` the exporter emits (ModelProto / GraphProto /
NodeProto / TensorProto / ValueInfoProto, with their published field
numbers) and decodes it back for verification. Field numbers follow the
public onnx.proto schema (ONNX IR v8 / opset 13 era).
"""
from __future__ import annotations

import struct

import numpy as np

# TensorProto.DataType
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE = 8, 9, 10, 11

_NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.float64): DOUBLE,
            np.dtype(np.int32): INT32, np.dtype(np.int64): INT64,
            np.dtype(np.bool_): BOOL, np.dtype(np.float16): FLOAT16,
            np.dtype(np.int8): INT8, np.dtype(np.uint8): UINT8}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


# ------------------------------------------------------------- encoding

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_int(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def f_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def f_str(field: int, value: str) -> bytes:
    return f_bytes(field, value.encode())


def f_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_FLOATS, A_INTS = 1, 2, 3, 4, 6, 7


def attribute(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    type=20."""
    body = f_str(1, name)
    if isinstance(value, bool):
        body += f_int(3, int(value)) + f_int(20, A_INT)
    elif isinstance(value, int):
        body += f_int(3, value) + f_int(20, A_INT)
    elif isinstance(value, float):
        body += f_float(2, value) + f_int(20, A_FLOAT)
    elif isinstance(value, str):
        body += f_bytes(4, value.encode()) + f_int(20, A_STRING)
    elif isinstance(value, np.ndarray):
        body += f_bytes(5, tensor("", value)) + f_int(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            body += b"".join(f_float(7, v) for v in value)
            body += f_int(20, A_FLOATS)
        else:
            body += b"".join(f_int(8, int(v)) for v in value)
            body += f_int(20, A_INTS)
    else:
        raise TypeError(f"attribute {name}: {type(value)}")
    return body


def tensor(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    dt = _NP2ONNX[arr.dtype]
    body = b"".join(f_int(1, d) for d in arr.shape)
    body += f_int(2, dt)
    if name:
        body += f_str(8, name)
    body += f_bytes(9, arr.tobytes())
    return body


def value_info(name: str, elem_type: int, shape) -> bytes:
    """ValueInfoProto{name=1, type=2{tensor_type=1{elem_type=1,
    shape=2{dim=1{dim_value=1}}}}}"""
    dims = b"".join(
        f_bytes(1, f_int(1, d) if isinstance(d, int) else f_str(2, str(d)))
        for d in shape)
    tshape = f_bytes(2, dims)
    ttype = f_bytes(1, f_int(1, elem_type) + tshape)
    return f_str(1, name) + f_bytes(2, ttype)


def node(op_type: str, inputs, outputs, name="", attrs=None) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    body = b"".join(f_str(1, i) for i in inputs)
    body += b"".join(f_str(2, o) for o in outputs)
    if name:
        body += f_str(3, name)
    body += f_str(4, op_type)
    for k, v in (attrs or {}).items():
        body += f_bytes(5, attribute(k, v))
    return body


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    body = b"".join(f_bytes(1, n) for n in nodes)
    body += f_str(2, name)
    body += b"".join(f_bytes(5, t) for t in initializers)
    body += b"".join(f_bytes(11, i) for i in inputs)
    body += b"".join(f_bytes(12, o) for o in outputs)
    return body


def model(graph_bytes: bytes, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7,
    opset_import=8{domain=1, version=2}."""
    body = f_int(1, 8)                       # IR version 8
    body += f_str(2, producer)
    body += f_bytes(7, graph_bytes)
    body += f_bytes(8, f_str(1, "") + f_int(2, opset))
    return body


# ------------------------------------------------------------- decoding

def _read_varint(buf, off):
    n = shift = 0
    while True:
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _fields(buf):
    """Yield (field, wire, value) over a protobuf message body."""
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, off = _read_varint(buf, off)
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[off:off + 4])[0]
            off += 4
        elif wire == 1:
            val = struct.unpack("<d", buf[off:off + 8])[0]
            off += 8
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, val


def decode_tensor(buf):
    dims, dt, name, raw, floats, int64s = [], FLOAT, "", None, [], []
    for field, wire, val in _fields(buf):
        if field == 1:
            dims.append(val)
        elif field == 2:
            dt = val
        elif field == 4:
            floats.append(val)
        elif field == 7:
            int64s.append(val)
        elif field == 8:
            name = val.decode()
        elif field == 9:
            raw = val
    np_dt = _ONNX2NP[dt]
    if raw is not None:
        arr = np.frombuffer(raw, np_dt).reshape(dims)
    elif floats:
        arr = np.asarray(floats, np_dt).reshape(dims)
    else:
        arr = np.asarray(int64s, np_dt).reshape(dims)
    return name, arr


def decode_attribute(buf):
    name, val, typ = "", None, None
    floats, ints = [], []
    for field, wire, v in _fields(buf):
        if field == 1:
            name = v.decode()
        elif field == 2:
            val = v
        elif field == 3:
            val = v
        elif field == 4:
            val = v.decode()
        elif field == 5:
            val = decode_tensor(v)[1]
        elif field == 7:
            floats.append(v)
        elif field == 8:
            ints.append(v)
        elif field == 20:
            typ = v
    if typ == A_FLOATS:
        val = floats
    elif typ == A_INTS:
        val = ints
    return name, val


def decode_node(buf):
    n = {"input": [], "output": [], "op_type": "", "name": "",
         "attrs": {}}
    for field, wire, val in _fields(buf):
        if field == 1:
            n["input"].append(val.decode())
        elif field == 2:
            n["output"].append(val.decode())
        elif field == 3:
            n["name"] = val.decode()
        elif field == 4:
            n["op_type"] = val.decode()
        elif field == 5:
            k, v = decode_attribute(val)
            n["attrs"][k] = v
    return n


def _decode_value_info(buf):
    name = ""
    for field, wire, val in _fields(buf):
        if field == 1:
            name = val.decode()
    return name


def decode_graph(buf):
    g = {"nodes": [], "name": "", "initializers": {}, "inputs": [],
         "outputs": []}
    for field, wire, val in _fields(buf):
        if field == 1:
            g["nodes"].append(decode_node(val))
        elif field == 2:
            g["name"] = val.decode()
        elif field == 5:
            n, arr = decode_tensor(val)
            g["initializers"][n] = arr
        elif field == 11:
            g["inputs"].append(_decode_value_info(val))
        elif field == 12:
            g["outputs"].append(_decode_value_info(val))
    return g


def decode_model(buf):
    m = {"ir_version": None, "producer": "", "graph": None, "opset": None}
    for field, wire, val in _fields(buf):
        if field == 1:
            m["ir_version"] = val
        elif field == 2:
            m["producer"] = val.decode()
        elif field == 7:
            m["graph"] = decode_graph(val)
        elif field == 8:
            for f2, w2, v2 in _fields(val):
                if f2 == 2:
                    m["opset"] = v2
    return m
