"""paddle.inference — the serving API surface.

Parity: `paddle/fluid/inference/api/` (`AnalysisConfig`,
`AnalysisPredictor`, `create_predictor`, zero-copy tensors). TPU-native:
the "optimized program" is the AOT StableHLO module exported by
`paddle_tpu.jit.save(..., input_spec=...)`; XLA plays the role of the IR
pass pipeline + TensorRT. The predictor wraps `TranslatedLayer` with the
reference's handle-based API so serving code ports.
"""
from __future__ import annotations

import numpy as np

from . import jit as _jit
from .core.tensor import Tensor


class Config:
    """AnalysisConfig parity (the knobs that are meaningful on TPU),
    plus the continuous-batching serving knobs
    (`enable_continuous_batching` -> `create_serving_engine`)."""

    def __init__(self, model_prefix=None, params_file=None):
        self.model_prefix = model_prefix
        self._use_tpu = True
        self._threads = 1
        self._ir_optim = True
        self._serving = None
        self._max_pending = None
        self._tensor_parallel = None
        self._expert_parallel = None
        self._num_replicas = None
        self._router_policy = None
        self._sampling = None
        self._prefill_replicas = None
        self._decode_replicas = None
        self._migration = None

    # -- continuous batching (paddle_tpu.serving) -------------------------
    def enable_continuous_batching(self, max_slots=None, block_size=None,
                                   num_blocks=None, max_seq_len=None,
                                   token_budget=None, eos_token_id=None,
                                   cache_dtype=None, kv_dtype=None,
                                   draft_k=None,
                                   draft_ngram=None, draft_ring=None,
                                   penalty_vocab_bins=None,
                                   prefix_caching=None,
                                   max_pending=None, sampling=None,
                                   tensor_parallel=None,
                                   expert_parallel=None,
                                   num_replicas=None,
                                   router_policy=None,
                                   prefill_replicas=None,
                                   decode_replicas=None,
                                   migration=None,
                                   max_adapters=None, lora_rank=None,
                                   lora_alpha=None,
                                   moe_weight_dtype=None,
                                   sparse_blocks=None,
                                   sparse_recent=None,
                                   ticks_per_dispatch=None):
        """Opt the predictor surface into the paged-KV continuous
        batching engine (docs/SERVING.md). The knobs mirror
        `serving.ServingEngine`; None keeps the engine default.
        `draft_k > 0` turns on speculative multi-token decoding: an
        n-gram prompt-lookup draft proposes up to `draft_k` tokens per
        decode and one verify pass scores them all (greedy verifies by
        token identity, sampling by the rejection rule).
        `prefix_caching=True` enables the radix-tree prefix KV cache
        (cross-request reuse of shared prompt heads).
        `kv_dtype="int8"` stores the paged KV pools quantized with
        per-entry-per-head fp32 scales — roughly 2.7x the resident
        tokens per chip vs fp32 pools at a documented bounded logit
        divergence (docs/SERVING.md "KV quantization"). `max_pending`
        bounds the async frontend's admission queue
        (`create_serving_frontend`) — see docs/SERVING.md.

        Distributed serving (docs/SERVING.md "Distributed serving"):
        `sampling` is a `serving.SamplingConfig` (or a dict of its
        fields — strategy/temperature/top_k/top_p/penalties; every
        strategy composes with speculation). `tensor_parallel > 1`
        shards the mixed step + KV pools over an `mp` mesh
        (`serving.distributed.TPServingEngine`); for MoE decoder
        stacks `expert_parallel > 1` additionally shards the experts
        over the `ep` rows of a 2-D (ep, mp) mesh (docs/MOE.md);
        `num_replicas > 1` plus `create_serving_router` puts a
        prefix-affinity `ReplicaRouter` in front of that many
        frontends (`router_policy`: "affinity" | "round_robin").

        Disaggregated prefill/decode serving (docs/SERVING.md,
        "Disaggregated serving"): `prefill_replicas`/`decode_replicas`
        (both >= 1, replacing `num_replicas`) split the fleet into
        prefill-role replicas — chunked prefill only, requests hand
        off at the first token with their paged KV blocks streamed
        over the block transport — and decode-role replicas that admit
        the migrated requests mid-stream (greedy outputs stay
        token-identical to a monolithic fleet; decode replicas get a
        decode-sized token budget and keep `draft_k` speculation).
        `migration=True` (or a dict of `ReplicaRouter.
        MIGRATION_DEFAULTS` overrides: imbalance/interval/max_per_tick)
        additionally lets loaded decode replicas SHED live requests to
        lighter siblings instead of preempting them.

        Multi-tenant serving (docs/SERVING.md "Multi-tenant serving",
        ISSUE 14): `max_adapters > 0` gives the engine fixed LoRA
        adapter slot tensors (slot 0 reserved for the base model) —
        `engine.register_adapter(...)` + `Request.adapter_id` serve K
        finetunes through the ONE compiled mixed step, with pin/LRU
        slot eviction and near-zero marginal HBM per tenant;
        `lora_rank`/`lora_alpha` size the slots. `moe_weight_dtype`
        ("int8" | "int4") quantizes a float MoE stack's EXPERT weights
        at engine build — int4 packs two nibbles per byte with
        per-(expert, out-channel) fp16 scales, dequantized at the
        matmul tile load (ops/pallas/grouped_matmul.py).

        Long-context serving (docs/SERVING.md "Long-context serving",
        ISSUE 15): `sparse_blocks=B` turns on block-sparse paged
        decode attention — every decode/verify query scores the
        candidate KV blocks against per-block min/max key summaries
        and attends only B top-scoring blocks plus the first block
        (attention sink) and a `sparse_recent`-block recency window;
        `B >= allocated blocks` is token-identical to dense and
        sparsity never recompiles. `kv_dtype="fp8_e4m3"` stores the
        pools as e4m3 bytes under the int8 scale plumbing — half of
        int8's fp32-baseline bytes again, composable with sparsity,
        TP sharding, transport and the prefix cache.

        Device-resident decode (docs/SERVING.md "Device-resident
        decode", ISSUE 18/19): `ticks_per_dispatch=N` runs up to N
        decode ticks per host dispatch inside ONE on-device
        `lax.while_loop` (token-identical to N=1; still exactly one
        compiled mixed step), `"auto"` lets the engine pace N from its
        measured host-gap/tick-time ratio. Speculation and penalized
        sampling ride INSIDE the loop: `draft_ring=W` sizes the
        per-slot device token ring the in-loop n-gram drafter scans
        (default 64; >= 2 when drafting), and `penalty_vocab_bins=Vb`
        sizes the per-slot token-count histogram the repetition/
        presence penalties read (default: full vocab = exact HF
        semantics; smaller Vb trades penalty precision for state via
        `token % Vb` binning). Impossible combos raise ValueError at
        engine build rather than silently degrading. In a
        disaggregated fleet, prefill replicas are pinned to 1 tick and
        decode replicas default to 4."""
        # validate BEFORE any assignment: a raising call must leave the
        # config exactly as it was (callers catch and retry)
        if kv_dtype is not None:
            from .serving.kv_cache import KV_DTYPES
            if str(kv_dtype) not in KV_DTYPES:
                raise ValueError(
                    f"kv_dtype={kv_dtype!r} not supported; pick one "
                    f"of {sorted(KV_DTYPES)}")
        if (prefill_replicas is not None) != (decode_replicas is not None):
            raise ValueError(
                "prefill_replicas and decode_replicas come as a pair "
                "(a disaggregated fleet needs both roles)")
        if prefill_replicas is not None and num_replicas is not None:
            raise ValueError(
                "pass either num_replicas (monolithic fleet) or "
                "prefill_replicas/decode_replicas (disaggregated), "
                "not both")
        if ticks_per_dispatch is not None and ticks_per_dispatch != "auto":
            if not isinstance(ticks_per_dispatch, int) \
                    or isinstance(ticks_per_dispatch, bool) \
                    or ticks_per_dispatch < 1:
                raise ValueError(
                    f"ticks_per_dispatch={ticks_per_dispatch!r} must be "
                    "an int >= 1 or 'auto'")
        if draft_k is not None and (not isinstance(draft_k, int)
                                    or isinstance(draft_k, bool)
                                    or draft_k < 0):
            raise ValueError(f"draft_k={draft_k!r} must be an int >= 0")
        if draft_ring is not None and (not isinstance(draft_ring, int)
                                       or isinstance(draft_ring, bool)
                                       or draft_ring < 2):
            raise ValueError(
                f"draft_ring={draft_ring!r} must be an int >= 2 (the "
                "n-gram scan needs at least one earlier token besides "
                "the tail)")
        if penalty_vocab_bins is not None \
                and (not isinstance(penalty_vocab_bins, int)
                     or isinstance(penalty_vocab_bins, bool)
                     or penalty_vocab_bins < 1):
            raise ValueError(
                f"penalty_vocab_bins={penalty_vocab_bins!r} must be "
                "an int >= 1")
        self._serving = dict(
            max_slots=max_slots, block_size=block_size,
            num_blocks=num_blocks, max_seq_len=max_seq_len,
            token_budget=token_budget, eos_token_id=eos_token_id,
            cache_dtype=cache_dtype, kv_dtype=kv_dtype, draft_k=draft_k,
            draft_ngram=draft_ngram, draft_ring=draft_ring,
            penalty_vocab_bins=penalty_vocab_bins,
            prefix_caching=prefix_caching,
            max_adapters=max_adapters, lora_rank=lora_rank,
            lora_alpha=lora_alpha, moe_weight_dtype=moe_weight_dtype,
            sparse_blocks=sparse_blocks, sparse_recent=sparse_recent,
            ticks_per_dispatch=ticks_per_dispatch)
        self._max_pending = max_pending
        self._tensor_parallel = tensor_parallel
        self._expert_parallel = expert_parallel
        self._num_replicas = num_replicas
        self._router_policy = router_policy
        self._sampling = sampling
        self._prefill_replicas = prefill_replicas
        self._decode_replicas = decode_replicas
        self._migration = migration
        return self

    def continuous_batching_enabled(self):
        return self._serving is not None

    def serving_config(self):
        return dict(self._serving) if self._serving else None

    # gpu/trt/mkldnn switches accepted as no-ops: XLA owns optimization
    def enable_use_gpu(self, memory_mb=100, device_id=0):
        pass

    def disable_gpu(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass

    def enable_mkldnn(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def enable_memory_optim(self):
        pass


class _IOTensor:
    """zero-copy paddle_infer.Tensor handle parity."""

    def __init__(self, name, store, idx):
        self.name = name
        self._store = store
        self._idx = idx

    def copy_from_cpu(self, arr):
        self._store[self._idx] = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass

    def copy_to_cpu(self):
        return np.asarray(self._store[self._idx])


class Predictor:
    def __init__(self, config: Config):
        if config.model_prefix is None:
            raise ValueError("Config needs a model path prefix")
        self._layer = _jit.load(config.model_prefix)
        n_inputs = len(self._layer.meta.get("input_spec") or [1])
        self._inputs = [None] * n_inputs
        self._outputs = []

    def get_input_names(self):
        return [f"input_{i}" for i in range(len(self._inputs))]

    def get_input_handle(self, name):
        idx = int(name.rsplit("_", 1)[-1]) if name.startswith("input_") \
            else 0
        return _IOTensor(name, self._inputs, idx)

    def run(self, inputs=None):
        if inputs is not None:
            self._inputs = [np.asarray(a) for a in inputs]
        outs = self._layer(*self._inputs)
        self._outputs = [o.numpy() if isinstance(o, Tensor) else
                         np.asarray(o) for o in outs]
        return self._outputs

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name):
        idx = int(name.rsplit("_", 1)[-1]) if name.startswith("output_") \
            else 0
        return _IOTensor(name, self._outputs, idx)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def _resolve_sampling(config: Config, sampling):
    if sampling is not None:
        return sampling
    sc = config._sampling
    if sc is None:
        return None
    if isinstance(sc, dict):
        from .serving.batcher import SamplingConfig
        return SamplingConfig(**sc)
    return sc


def create_serving_engine(config: Config, model, sampling=None, seed=0,
                          mesh=None, **overrides):
    """Build a continuous-batching `serving.ServingEngine` from an
    `enable_continuous_batching()` config and a causal-LM serving model
    (`models.gpt.GPTForGeneration` or anything exposing the same
    `_gen_tensors`/decoder contract). This is the batch-serving mode of
    the AnalysisPredictor surface: one resident engine, many concurrent
    requests, instead of one `Predictor.run` per fixed-shape batch.

    With `tensor_parallel > 1` on the config the engine is a
    `serving.distributed.TPServingEngine`: same host loop, mixed step
    and KV pools sharded over an `mp` mesh (`mesh` overrides the
    default `parallel.mp_layers.tp_mesh` device pick). `overrides`
    replace individual engine kwargs after the config — the
    disaggregated `create_serving_router` uses this to give each
    replica its role (and prefill replicas `draft_k=0`)."""
    if not config.continuous_batching_enabled():
        raise ValueError(
            "call config.enable_continuous_batching(...) first")
    kw = {k: v for k, v in config.serving_config().items()
          if v is not None}
    kw.update(overrides)
    sampling = _resolve_sampling(config, sampling)
    tp = int(config._tensor_parallel or 1)
    ep = int(config._expert_parallel or 1)
    if tp > 1 or ep > 1:
        from .serving.distributed.tp_engine import TPServingEngine
        return TPServingEngine(model, tensor_parallel=tp,
                               expert_parallel=ep, mesh=mesh,
                               sampling=sampling, seed=seed, **kw)
    from .serving.engine import ServingEngine
    return ServingEngine(model, sampling=sampling, seed=seed, **kw)


def create_serving_router(config: Config, model, sampling=None, seed=0):
    """Build the multi-replica serving stack: `num_replicas` engines
    (tensor-parallel when `tensor_parallel > 1`; replica r takes the
    next `tp` local devices, wrapping around) each behind a
    `ServingFrontend`, fronted by a prefix-affinity
    `serving.distributed.ReplicaRouter`. `async with router:` starts
    every replica's step loop plus the health prober;
    `submit()`/`stream()` dispatch with affinity, load balancing and
    failover (docs/SERVING.md "Distributed serving").

    With `prefill_replicas`/`decode_replicas` on the config the fleet
    is DISAGGREGATED instead: prefill-role engines (chunked prefill
    only, `draft_k` forced to 0) hand requests off at the first token
    over the KV block transport to decode-role engines (decode-sized
    token budgets, speculation kept), and `migration=` enables
    router-driven load shedding between decode replicas
    (docs/SERVING.md "Disaggregated serving")."""
    if not config.continuous_batching_enabled():
        raise ValueError(
            "call config.enable_continuous_batching(...) first")
    roles = None
    if config._prefill_replicas is not None:
        p, d = int(config._prefill_replicas), int(config._decode_replicas)
        if p < 1 or d < 1:
            raise ValueError(
                f"a disaggregated fleet needs prefill_replicas >= 1 "
                f"and decode_replicas >= 1, got {p}/{d}")
        roles = ["prefill"] * p + ["decode"] * d
        n = p + d
    else:
        n = int(config._num_replicas or 1)
        if n < 1:
            raise ValueError(f"num_replicas must be >= 1, got {n}")
    from .serving.distributed.router import ReplicaRouter
    from .serving.frontend import ServingFrontend
    tp = int(config._tensor_parallel or 1)
    ep = int(config._expert_parallel or 1)
    meshes = [None] * n
    if tp > 1 or ep > 1:
        import jax

        from .parallel.mp_layers import tp_ep_mesh, tp_mesh
        devices = jax.devices()
        world = tp * ep
        picks = [[devices[(r * world + i) % len(devices)]
                  for i in range(world)] for r in range(n)]
        # MoE stacks always serve over the 2-D (ep, mp) mesh, even at
        # expert_parallel=1 (the expert param specs name the ep axis)
        moe = bool(getattr(getattr(model, "decoder", None),
                           "_num_experts", 0))
        if ep > 1 or moe:
            meshes = [tp_ep_mesh(tp, ep, devices=d) for d in picks]
        else:
            meshes = [tp_mesh(tp, devices=d) for d in picks]
    fkw = {}
    if config._max_pending is not None:
        fkw["max_pending"] = int(config._max_pending)

    def _overrides(r):
        if roles is None:
            return {}
        if roles[r] == "prefill":
            # prefill replicas never decode past the first token, so
            # speculation would only waste the reserved verify region
            # — and in a block-sparse fleet they likewise skip the
            # sparse decode region while still MAINTAINING the block
            # summaries (track_summaries), so their exported blocks
            # match a sparse decode replica's kv_meta geometry
            # ... and a chunked-prefill-only replica never has a
            # pure-decode plan, so multi-tick dispatches would just
            # stage dead control tensors: pin it to 1 tick
            ov = {"role": "prefill", "draft_k": 0,
                  "ticks_per_dispatch": 1}
            if (config.serving_config() or {}).get("sparse_blocks"):
                ov.update(sparse_blocks=None, track_summaries=True)
            return ov
        ov = {"role": "decode"}
        if (config.serving_config() or {}).get(
                "ticks_per_dispatch") is None:
            # decode replicas are where the host-dispatch gap lives —
            # default them onto the device-resident loop
            ov["ticks_per_dispatch"] = 4
        return ov

    frontends = [ServingFrontend(
        create_serving_engine(config, model, sampling=sampling,
                              seed=seed, mesh=meshes[r],
                              **_overrides(r)), **fkw)
        for r in range(n)]
    rkw = {}
    if config._router_policy is not None:
        rkw["policy"] = config._router_policy
    if roles is not None:
        rkw["roles"] = roles
    if config._migration is not None:
        rkw["migration"] = config._migration
    return ReplicaRouter(frontends, **rkw)


def create_serving_frontend(config: Config, model, sampling=None,
                            seed=0):
    """Build the asyncio multi-tenant ingress over a fresh serving
    engine: `await frontend.start()` (or `async with frontend:`) spawns
    the background step-loop task; `submit()`/`stream()` are the
    per-request API (bounded admission, per-tenant fairness, deadlines,
    cancellation — docs/SERVING.md). `max_pending` from
    `enable_continuous_batching` bounds the admission queue."""
    engine = create_serving_engine(config, model, sampling=sampling,
                                   seed=seed)
    from .serving.frontend import ServingFrontend
    kw = {}
    if config._max_pending is not None:
        kw["max_pending"] = int(config._max_pending)
    return ServingFrontend(engine, **kw)


def create_fleet_controller(config: Config, model, sampling=None,
                            seed=0, *, bundle=None, bundle_root=None,
                            version="v1", spill_dir=None,
                            export=True):
    """Build the fleet control plane (ISSUE 17) over a
    `create_serving_router` fleet: a `serving.fleet.FleetController`
    that can AOT-boot replicas from a versioned bundle with zero
    mixed-step compiles, roll weight upgrades through the router's
    quiesce plane, and actuate the SLO autoscaler's decisions.

    `bundle` names an existing bundle directory (or passes a loaded
    `FleetBundle`); otherwise, with `export=True`, a bundle for
    `version` is exported under `bundle_root` (default: next to the
    persistent kernel-autotune cache) from replica 0's engine.
    Returns `(router, controller)` — boot the fleet with
    `async with router:`, then drive `controller.boot_replica()` /
    `rolling_upgrade()` / an attached `SLOAutoscaler`
    (docs/DEPLOYMENT.md)."""
    from .serving.fleet import (FleetBundle, FleetController,
                                export_bundle)
    router = create_serving_router(config, model, sampling=sampling,
                                   seed=seed)
    if bundle is None and export:
        bdir = export_bundle(router.frontends[0].engine,
                             bundle_root, version=str(version),
                             seed=seed)
        bundle = FleetBundle(bdir)
    kw = {}
    if config._max_pending is not None:
        kw["max_pending"] = int(config._max_pending)
    return router, FleetController(router, bundle,
                                   spill_dir=spill_dir, **kw)
