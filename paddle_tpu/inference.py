"""paddle.inference — the serving API surface.

Parity: `paddle/fluid/inference/api/` (`AnalysisConfig`,
`AnalysisPredictor`, `create_predictor`, zero-copy tensors). TPU-native:
the "optimized program" is the AOT StableHLO module exported by
`paddle_tpu.jit.save(..., input_spec=...)`; XLA plays the role of the IR
pass pipeline + TensorRT. The predictor wraps `TranslatedLayer` with the
reference's handle-based API so serving code ports.
"""
from __future__ import annotations

import numpy as np

from . import jit as _jit
from .core.tensor import Tensor


class Config:
    """AnalysisConfig parity (the knobs that are meaningful on TPU),
    plus the continuous-batching serving knobs
    (`enable_continuous_batching` -> `create_serving_engine`)."""

    def __init__(self, model_prefix=None, params_file=None):
        self.model_prefix = model_prefix
        self._use_tpu = True
        self._threads = 1
        self._ir_optim = True
        self._serving = None
        self._max_pending = None

    # -- continuous batching (paddle_tpu.serving) -------------------------
    def enable_continuous_batching(self, max_slots=None, block_size=None,
                                   num_blocks=None, max_seq_len=None,
                                   token_budget=None, eos_token_id=None,
                                   cache_dtype=None, draft_k=None,
                                   draft_ngram=None, prefix_caching=None,
                                   max_pending=None):
        """Opt the predictor surface into the paged-KV continuous
        batching engine (docs/SERVING.md). The knobs mirror
        `serving.ServingEngine`; None keeps the engine default.
        `draft_k > 0` turns on speculative multi-token decoding (greedy
        only): an n-gram prompt-lookup draft proposes up to `draft_k`
        tokens per decode and one verify pass scores them all.
        `prefix_caching=True` enables the radix-tree prefix KV cache
        (cross-request reuse of shared prompt heads). `max_pending`
        bounds the async frontend's admission queue
        (`create_serving_frontend`) — see docs/SERVING.md."""
        self._serving = dict(
            max_slots=max_slots, block_size=block_size,
            num_blocks=num_blocks, max_seq_len=max_seq_len,
            token_budget=token_budget, eos_token_id=eos_token_id,
            cache_dtype=cache_dtype, draft_k=draft_k,
            draft_ngram=draft_ngram, prefix_caching=prefix_caching)
        self._max_pending = max_pending
        return self

    def continuous_batching_enabled(self):
        return self._serving is not None

    def serving_config(self):
        return dict(self._serving) if self._serving else None

    # gpu/trt/mkldnn switches accepted as no-ops: XLA owns optimization
    def enable_use_gpu(self, memory_mb=100, device_id=0):
        pass

    def disable_gpu(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass

    def enable_mkldnn(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def enable_memory_optim(self):
        pass


class _IOTensor:
    """zero-copy paddle_infer.Tensor handle parity."""

    def __init__(self, name, store, idx):
        self.name = name
        self._store = store
        self._idx = idx

    def copy_from_cpu(self, arr):
        self._store[self._idx] = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass

    def copy_to_cpu(self):
        return np.asarray(self._store[self._idx])


class Predictor:
    def __init__(self, config: Config):
        if config.model_prefix is None:
            raise ValueError("Config needs a model path prefix")
        self._layer = _jit.load(config.model_prefix)
        n_inputs = len(self._layer.meta.get("input_spec") or [1])
        self._inputs = [None] * n_inputs
        self._outputs = []

    def get_input_names(self):
        return [f"input_{i}" for i in range(len(self._inputs))]

    def get_input_handle(self, name):
        idx = int(name.rsplit("_", 1)[-1]) if name.startswith("input_") \
            else 0
        return _IOTensor(name, self._inputs, idx)

    def run(self, inputs=None):
        if inputs is not None:
            self._inputs = [np.asarray(a) for a in inputs]
        outs = self._layer(*self._inputs)
        self._outputs = [o.numpy() if isinstance(o, Tensor) else
                         np.asarray(o) for o in outs]
        return self._outputs

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs) or 1)]

    def get_output_handle(self, name):
        idx = int(name.rsplit("_", 1)[-1]) if name.startswith("output_") \
            else 0
        return _IOTensor(name, self._outputs, idx)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def create_serving_engine(config: Config, model, sampling=None, seed=0):
    """Build a continuous-batching `serving.ServingEngine` from an
    `enable_continuous_batching()` config and a causal-LM serving model
    (`models.gpt.GPTForGeneration` or anything exposing the same
    `_gen_tensors`/decoder contract). This is the batch-serving mode of
    the AnalysisPredictor surface: one resident engine, many concurrent
    requests, instead of one `Predictor.run` per fixed-shape batch."""
    if not config.continuous_batching_enabled():
        raise ValueError(
            "call config.enable_continuous_batching(...) first")
    from .serving.engine import ServingEngine
    kw = {k: v for k, v in config.serving_config().items()
          if v is not None}
    return ServingEngine(model, sampling=sampling, seed=seed, **kw)


def create_serving_frontend(config: Config, model, sampling=None,
                            seed=0):
    """Build the asyncio multi-tenant ingress over a fresh serving
    engine: `await frontend.start()` (or `async with frontend:`) spawns
    the background step-loop task; `submit()`/`stream()` are the
    per-request API (bounded admission, per-tenant fairness, deadlines,
    cancellation — docs/SERVING.md). `max_pending` from
    `enable_continuous_batching` bounds the admission queue."""
    engine = create_serving_engine(config, model, sampling=sampling,
                                   seed=seed)
    from .serving.frontend import ServingFrontend
    kw = {}
    if config._max_pending is not None:
        kw["max_pending"] = int(config._max_pending)
    return ServingFrontend(engine, **kw)
