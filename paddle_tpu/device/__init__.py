"""paddle_tpu.device — `python/paddle/device/` parity (set_device, streams,
memory stats). Device memory is owned by XLA/PJRT; stats come from
jax's device memory profile.
"""
from __future__ import annotations

import jax

from ..core.place import (set_device, get_device, CPUPlace, TPUPlace,  # noqa
                          CUDAPlace)


def get_all_device_type():
    return ["cpu", "tpu"]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def device_count():
    return jax.device_count()


class _MemStats:
    def _stats(self, device_id=0):
        try:
            d = jax.devices()[device_id]
            return d.memory_stats() or {}
        except Exception:
            return {}


_mem = _MemStats()


def memory_allocated(device=None):
    return _mem._stats().get("bytes_in_use", 0)


def max_memory_allocated(device=None):
    return _mem._stats().get("peak_bytes_in_use", 0)


def memory_reserved(device=None):
    return _mem._stats().get("bytes_reserved",
                             _mem._stats().get("bytes_in_use", 0))


def max_memory_reserved(device=None):
    return max_memory_allocated(device)


def empty_cache():
    pass


def synchronize(device=None):
    """device synchronize — block until all queued work completes."""
    import jax.numpy as jnp
    jax.block_until_ready(jnp.zeros(()))


# paddle.device.cuda shim so ported code keeps working on TPU
class cuda:
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)
    empty_cache = staticmethod(empty_cache)
    synchronize = staticmethod(synchronize)

    @staticmethod
    def device_count():
        return device_count()

    class Event:
        def __init__(self, *a, **k):
            self._t = None

        def record(self, stream=None):
            import time
            synchronize()
            self._t = time.perf_counter()

    class Stream:
        def __init__(self, *a, **k):
            pass


def get_cudnn_version():
    """`device/__init__.py get_cudnn_version` parity: None on builds
    without cuDNN (every TPU build)."""
    return None


def disable_signal_handler():
    """Parity shim: the reference unhooks its C++ fault handlers; this
    build installs none, so there is nothing to unhook."""
    return None
