"""Canonical PartitionSpec form — ONE definition of "what jax will
normalize a spec to", shared by the runtime call sites that must agree
on jit-cache identity and by the recompile-hazard lint pass.

The hazard (learned three times over: PR 7 hybrid step outputs, PR 8
trailing-None pool specs, PR 10 EP-mesh ``P()`` collapse): the jit
cache keys on *input shardings*, and two placement-IDENTICAL specs
written differently — ``P('a')`` vs ``P('a', None)``, or
``P(None, None, None, 'mp')`` on a size-1 ``mp`` axis vs ``P()`` —
are DIFFERENT cache keys (verified on this container's jax 0.4.37:
feeding a ``device_put`` placed with one form into a jit whose
previous call saw the other form compiles a second executable).
Whenever a step's output arrays are fed back as the next call's
inputs, the initial ``device_put`` spec and the step's out-spec must
therefore be written in one agreed normal form, or step 2 silently
pays a full recompile.

``canonicalize_spec`` IS that normal form:

* entries naming only size-1 mesh axes are dropped (a size-1 axis
  shards nothing — GSPMD-inferred output specs omit it, which is the
  EP-mesh ``P(None,None,None,'mp')`` -> ``P()`` collapse at tp=1);
* tuple entries lose their size-1 members, a singleton tuple unwraps
  to its bare axis name, an emptied tuple becomes ``None``;
* trailing ``None`` entries are trimmed (the PR 8 pool-spec lesson);
* an all-``None`` spec collapses to ``P()``.

The static-analysis side (``analysis.rules`` RH201/RH202) shares the
trim/collapse logic through ``literal_is_canonical`` so the lint rule
and the runtime code cannot drift apart.
"""
from __future__ import annotations

#: sentinel for spec-literal entries the AST pass cannot evaluate
#: (names, calls, starred expressions) — treated as "shards something",
#: i.e. never trimmable
OPAQUE = object()


def _axis_sizes(mesh):
    """{axis name: size} from a Mesh, a dict, or None (unknown)."""
    if mesh is None:
        return None
    if isinstance(mesh, dict):
        return dict(mesh)
    return dict(mesh.shape)


def _canon_entries(entries, sizes):
    """Core normal-form transform over a list of spec entries. Entries
    are None, axis-name strings, tuples of axis names, or OPAQUE."""
    out = []
    for e in entries:
        if e is None:
            out.append(None)
            continue
        if e is OPAQUE:
            out.append(e)
            continue
        names = e if isinstance(e, tuple) else (e,)
        if sizes is not None:
            names = tuple(n for n in names
                          if n is OPAQUE or sizes.get(n, 0) != 1)
        if not names:
            out.append(None)
        elif len(names) == 1:
            out.append(names[0])
        else:
            out.append(names)
    while out and out[-1] is None:
        out.pop()
    return out


def canonicalize_spec(spec, mesh=None):
    """The canonical `PartitionSpec` for `spec` under `mesh`.

    `mesh` may be a `jax.sharding.Mesh`, a `{axis: size}` dict, or
    None (sizes unknown — size-1 dropping is skipped, trimming still
    applies). Idempotent; placement-equivalent to the input by
    construction (only non-sharding syntax is removed)."""
    from jax.sharding import PartitionSpec as P
    return P(*_canon_entries(list(spec), _axis_sizes(mesh)))


def canonical_sharding(mesh, spec):
    """`NamedSharding(mesh, canonicalize_spec(spec, mesh))` — the
    device_put / out_shardings constructor every feed-outputs-back-in
    call site should use."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, canonicalize_spec(spec, mesh))


def literal_is_canonical(entries):
    """Lint-side check over AST-extracted spec-literal entries (None /
    str / tuple-of-str / OPAQUE): is the literal already in normal
    form for EVERY mesh? Mesh-independent only — size-1 axis dropping
    needs runtime sizes, so a spec naming axes is never flagged for
    that (``canonicalize_spec`` at the call site is the fix the rule
    suggests). Returns (ok, why)."""
    ents = list(entries)
    if ents and all(e is None for e in ents):
        return False, ("all-None spec: jax treats it as P() in "
                       "sharding identity but NOT in jit cache keys — "
                       "write P() (or canonicalize_spec)")
    if ents and ents[-1] is None:
        return False, ("trailing-None spec: placement-identical to "
                       "the trimmed form but a DIFFERENT jit cache "
                       "key — trim it (or canonicalize_spec)")
    for e in ents:
        if isinstance(e, tuple) and len(e) == 1:
            return False, ("singleton-tuple entry: P(('a',)) and "
                           "P('a') are different cache keys — unwrap "
                           "it (or canonicalize_spec)")
    return True, ""
