"""Runtime trace-discipline sanitizers: transfer guard + compile-count
watchdog (+ optional NaN debug), as one `sanitize()` context.

The static pass (tracelint) proves what it can from the AST; these
guards catch the rest AT RUNTIME, cheaply enough to stay on for the
whole tier-1 suite (wired in tests/conftest.py) and for every
tools/*_smoke.py run:

* **Transfer guard** — jax's own implicit-transfer tripwire. Suite
  default guards DEVICE-TO-HOST only: an implicit d2h (``float(x)``,
  ``.item()`` on a device array mid-hot-loop) is the classic hidden
  sync that serializes a serving step, and explicit ``device_get`` /
  ``np.asarray`` stay allowed, so the host loops keep working.
  Host-to-device can NOT be globally disallowed — eager ops
  materialize scalar constants via h2d on every call (verified on
  this jax: even ``x * 2.0`` trips) — so h2d guarding is opt-in
  (`guard_scope=("all",)`) for targeted tests. On the CPU test
  backend d2h transfers are free and never trip: the suite-wide
  guard is a no-op there by construction and a real tripwire on
  device backends. A guard error crossing the context boundary
  increments `paddle_tpu_compile_watchdog_transfer_guard_trips_total`.

* **Compile-count watchdog** — budgets per `instrumented_jit` name,
  counted PER JIT INSTANCE (each `instrumented_jit(...)` wrapper gets
  its own monotonically-issued id), fed by the PR 1 compile
  accounting in `jit/functional.py`. "The ONE jitted mixed step
  compiles exactly once per engine" becomes enforceable: budget
  ``serving_mixed_step=1`` means each engine's OWN step wrapper may
  compile once — N engines in one test are each allowed their one
  compile, while a spec-mismatch second compile of any single engine
  is a recorded violation (and fails the test via the conftest
  fixture). Violations increment
  `paddle_tpu_compile_watchdog_budget_exceeded_total{fn=...}`.

Env contract (docs/ANALYSIS.md): ``PADDLE_TPU_GUARDS=0`` disables the
suite-wide wiring; ``=1``/unset enables transfer guard + watchdog;
``=nan`` additionally flips ``jax_debug_nans`` for the guarded scope.
"""
from __future__ import annotations

import contextlib
import dataclasses
import itertools
import os
import threading
from typing import Dict, List, Optional

from ..profiler.metrics import (COMPILE_WATCHDOG_BUDGET_EXCEEDED,
                                TRANSFER_GUARD_TRIPS)

#: per-instrumented_jit-name compile budgets PER JIT INSTANCE. Only
#: entries with a hard one-compile contract belong here: names whose
#: instances legitimately compile per shape signature (gen_prefill,
#: HybridGPT.train_many's static k, ...) stay unbudgeted.
DEFAULT_BUDGETS: Dict[str, int] = {
    # one mixed step per engine — tests/test_serving.py's contract.
    # The multi-tick while_loop wrapper (ISSUE 18) shares this name
    # and this budget: n_ticks is a traced scalar, so 1-tick mixed
    # and N-tick pure-decode dispatches run the same executable
    "serving_mixed_step": 1,
    # one fixed-shape pool copy per PagedKVCache (prefix-cache CoW)
    "serving_prefix_cow": 1,
    # one fixed-shape slot write per AdapterCache — every LoRA load/
    # evict-reload reuses it (tools/lora_smoke.py's contract)
    "serving_adapter_load": 1,
    # one fixed-shape checkpoint cast per engine — every rolling-
    # upgrade flip reuses it (tools/fleet_smoke.py's contract)
    "serving_weight_swap": 1,
    # one fixed-shape SAGE train step per trainer — the GraphEngine's
    # [B, fanout] bundle contract keeps every batch the same shape
    # (tools/graph_smoke.py's contract)
    "graph_sage_step": 1,
}

_id_counter = itertools.count(1)


def next_instance_id():
    """Monotonic id for one jitted wrapper (id() reuse after GC would
    merge two instances' counts)."""
    return next(_id_counter)


@dataclasses.dataclass
class BudgetViolation:
    name: str
    instance: int
    count: int
    budget: int

    def __str__(self):
        return (f"jit entry '{self.name}' (instance {self.instance}) "
                f"compiled {self.count}x, budget {self.budget} — a "
                "spec/signature mismatch is forcing a silent "
                "recompile (docs/ANALYSIS.md)")


class CompileWatchdog:
    """Per-(name, instance) compile counting against budgets."""

    def __init__(self, budgets=None):
        self.budgets = dict(DEFAULT_BUDGETS)
        if budgets:
            self.budgets.update(budgets)
        self.violations: List[BudgetViolation] = []
        self._counts: Dict[tuple, int] = {}
        self._violated: Dict[tuple, BudgetViolation] = {}
        self._lock = threading.Lock()

    def note_compile(self, name, instance, n=1):
        budget = self.budgets.get(name)
        with self._lock:
            key = (name, instance)
            c = self._counts[key] = self._counts.get(key, 0) + n
            if budget is not None and c > budget:
                # ONE violation (and one metric tick) per (name,
                # instance) — a persistently-recompiling entry updates
                # its count instead of repeating the same root cause
                v = self._violated.get(key)
                if v is None:
                    v = BudgetViolation(name, instance, c, budget)
                    self._violated[key] = v
                    self.violations.append(v)
                    COMPILE_WATCHDOG_BUDGET_EXCEEDED.labels(name).inc()
                else:
                    v.count = c

    def check(self):
        """Raise on any recorded violation (explicit-check style; the
        conftest fixture prefers reading `.violations` to fail the
        test with every violation listed)."""
        if self.violations:
            raise RuntimeError("; ".join(str(v)
                                         for v in self.violations))

    def consume_violations(self):
        """Return and clear — for tests that DELIBERATELY trigger a
        violation and must not fail their own teardown."""
        with self._lock:
            out, self.violations = self.violations, []
            self._violated.clear()
        return out


# active watchdog stack (sanitize() nests: conftest wraps every test,
# the smoke tools wrap their own runs inside that)
_STACK: List[CompileWatchdog] = []
_STACK_LOCK = threading.Lock()


def active() -> bool:
    return bool(_STACK)


def current() -> Optional[CompileWatchdog]:
    return _STACK[-1] if _STACK else None


def notify_compile(name, instance, n=1):
    """Called by instrumented_jit when a wrapper observes fresh
    compiles; fans out to every active watchdog (nested scopes each
    keep their own books)."""
    with _STACK_LOCK:
        watchers = list(_STACK)
    for wd in watchers:
        wd.note_compile(name, instance, n)


def is_transfer_guard_error(exc) -> bool:
    s = str(exc)
    return "transfer" in s and ("Disallowed" in s or "disallow" in s)


def note_exception(exc) -> bool:
    """Count `exc` against the transfer-guard trip metric when it is
    a guard error; returns whether it was one. `sanitize` calls this
    for exceptions crossing its own boundary, but a pytest test
    body's exception never unwinds through a yield fixture — the
    conftest wiring reports it from a `pytest_runtest_makereport`
    hook instead, so the metric moves on device backends where the
    suite-wide d2h guard actually trips. Counting is idempotent per
    exception OBJECT (marked on first count): one trip seen by both
    an inner sanitize scope and the makereport hook increments
    once."""
    if exc is None or not is_transfer_guard_error(exc):
        return False
    if not getattr(exc, "_paddle_tpu_trip_counted", False):
        try:
            exc._paddle_tpu_trip_counted = True
        except Exception:
            pass
        TRANSFER_GUARD_TRIPS.inc()
    return True


@contextlib.contextmanager
def sanitize(transfer_guard="disallow", guard_scope=("device_to_host",),
             budgets=None, nan_debug=False, watchdog=True):
    """The combined sanitizer context. Yields the CompileWatchdog (or
    None with watchdog=False).

    `transfer_guard`: jax guard level ("disallow" | "log" | None=off).
    `guard_scope`: transfer directions to guard — any of
    "device_to_host", "host_to_device", "device_to_device", or "all".
    `budgets`: overrides merged over DEFAULT_BUDGETS.
    `nan_debug`: flip jax_debug_nans inside the scope.
    """
    import jax

    wd = CompileWatchdog(budgets) if watchdog else None
    scopes = {
        "device_to_host": jax.transfer_guard_device_to_host,
        "host_to_device": jax.transfer_guard_host_to_device,
        "device_to_device": jax.transfer_guard_device_to_device,
        "all": jax.transfer_guard,
    }
    old_nan = jax.config.jax_debug_nans
    with contextlib.ExitStack() as stack:
        if transfer_guard:
            for s in guard_scope:
                stack.enter_context(scopes[s](transfer_guard))
        if nan_debug:
            jax.config.update("jax_debug_nans", True)
        if wd is not None:
            with _STACK_LOCK:
                _STACK.append(wd)
        try:
            yield wd
        except Exception as e:
            note_exception(e)
            raise
        finally:
            if wd is not None:
                with _STACK_LOCK:
                    _STACK.remove(wd)
            if nan_debug:
                jax.config.update("jax_debug_nans", old_nan)


def from_env(default="1"):
    """kwargs for `sanitize()` from the PADDLE_TPU_GUARDS env knob
    (docs/ANALYSIS.md), or None when guards are disabled."""
    v = os.environ.get("PADDLE_TPU_GUARDS", default).strip().lower()
    if v in ("0", "off", "false", "no"):
        return None
    kw = {}
    if v == "nan":
        kw["nan_debug"] = True
    return kw
