"""Tracelint rule catalog.

Every rule descends from a bug this repo actually shipped and fixed
by hand (the CHANGES.md lore notes cited per rule in
docs/ANALYSIS.md); tracelint turns each one into a machine-checked
invariant. Two families:

* **TL1xx — trace-safety**: patterns inside functions the call-graph
  pass proved run under a jax trace. Context-free rules (host calls,
  state mutation, ``.item()``) apply to every traced function; the
  dataflow-lite rules (branching on / casting a traced value) apply
  only to TRACE ENTRIES, whose parameters are known-traced (minus
  ``static_argnums``) — transitive callees may legitimately receive
  static config, so flagging them would drown the signal.
* **RH2xx — recompile hazards**: module-level checks for the
  spec-normalization and weak-type pitfalls that made a second,
  silent compile of "the ONE jitted step". These share their
  normal-form logic with the runtime through
  ``analysis.specs.literal_is_canonical``.

The analysis is deliberately an UNDER-approximation (it only fires on
patterns it can prove are inside a trace) — precision over recall, so
`tools/tracelint.py --check` stays a hard CI gate with a near-empty
allowlist.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

from . import specs as _specs
from .callgraph import FunctionInfo, ModuleIndex, _dotted

#: rule id -> one-line summary (the meta-test asserts each id is
#: documented in docs/ANALYSIS.md)
RULES = {
    "TL101": "host call inside a traced function (time.*, np.random, "
             "os.environ/getenv, open, input)",
    "TL102": "host materialization of a traced value (.item(), "
             "float()/int()/bool() on a traced argument)",
    "TL103": "python branch (if/while) on a traced value",
    "TL104": "mutation of closure/global state inside a traced "
             "function",
    "TL105": "unhashable (list/dict/set) static argument to a jitted "
             "callable",
    "TL106": "donated buffer read after the donating call",
    "TL107": "host escape (host call, jax.device_get, .item(), "
             ".block_until_ready(), .copy_to_host_async()) inside a "
             "lax.scan/while_loop body or a function it calls",
    "RH201": "non-canonical PartitionSpec (trailing None / singleton "
             "tuple) in a jit-boundary sharding",
    "RH202": "all-None PartitionSpec where jax's cache key wants P()",
    "RH203": "bare python number passed to a jitted callable "
             "(weak-type literal: a dtype-flipping caller recompiles)",
}


@dataclasses.dataclass
class Finding:
    rule: str
    relpath: str
    qualname: str
    lineno: int
    message: str

    @property
    def key(self):
        """Allowlist identity: stable across line-number churn."""
        return f"{self.rule}:{self.relpath}:{self.qualname}"

    def to_dict(self):
        return dataclasses.asdict(self)


# --------------------------------------------------------- trace rules

#: module roots whose calls are host-only inside a trace
_HOST_MODULES = {
    "time": ("time", "perf_counter", "monotonic", "sleep",
             "process_time", "time_ns", "perf_counter_ns"),
    "random": None,          # all of python stdlib random
    "np.random": None,
    "numpy.random": None,
}
_HOST_BUILTINS = {"open", "input"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "popitem", "setdefault", "remove", "discard", "clear",
             "appendleft", "write"}


def _resolved(module: ModuleIndex, node):
    return module.resolve_alias(_dotted(node))


def _is_host_call(module, call):
    name = _resolved(module, call.func)
    if name is None:
        if isinstance(call.func, ast.Name) \
                and call.func.id in _HOST_BUILTINS:
            return call.func.id
        return None
    if name in _HOST_BUILTINS:
        return name
    if name in ("os.getenv", "os.environb.get"):
        return name
    if name.startswith("os.environ."):
        return name
    for root, members in _HOST_MODULES.items():
        rootdot = root + "."
        if name == root or name.startswith(rootdot):
            if members is None:
                return name
            tail = name[len(rootdot):]
            if tail in members:
                return name
    return None


def _fn_body(fn: FunctionInfo):
    if isinstance(fn.node, ast.Lambda):
        return [fn.node.body]
    return fn.node.body


def _walk_own(fn: FunctionInfo):
    """Walk a function's body WITHOUT descending into nested function
    definitions (they are separate FunctionInfos and get their own
    pass if traced) — including nested defs that sit directly in the
    body statement list."""
    stack = list(_fn_body(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _local_names(fn: FunctionInfo):
    """Names that are local to the function (params + anything bound
    in its body, python scoping rules minus global/nonlocal)."""
    local = set(fn.params)
    node = fn.node
    if isinstance(node, ast.Lambda):
        a = node.args
        return local | {p.arg for p in a.posonlyargs + a.args
                        + a.kwonlyargs} \
            | ({a.vararg.arg} if a.vararg else set()) \
            | ({a.kwarg.arg} if a.kwarg else set())
    a = node.args
    local |= {p.arg for p in a.kwonlyargs}
    if a.vararg:
        local.add(a.vararg.arg)
    if a.kwarg:
        local.add(a.kwarg.arg)
    declared = set()
    for n in _walk_own(fn):
        if isinstance(n, (ast.Global, ast.Nonlocal)):
            declared.update(n.names)
        elif isinstance(n, ast.Name) and isinstance(
                n.ctx, (ast.Store, ast.Del)):
            local.add(n.id)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    local.add(t.id)
        elif isinstance(n, (ast.comprehension,)):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    local.add(t.id)
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    for t in ast.walk(item.optional_vars):
                        if isinstance(t, ast.Name):
                            local.add(t.id)
    local |= set(fn.nested)
    return local - declared


def _traced_params(fn: FunctionInfo):
    """Parameter names known to carry traced values: trace entries
    only, minus static_argnums, minus leading params bound by
    `functools.partial` at the trace root (partial-bound args are
    closed over host-side — the `jit(partial(init_params, cfg))`
    idiom), and minus `self`/`cls`."""
    if not fn.trace_entry:
        return set()
    params = [p for p in fn.params if p not in ("self", "cls")]
    return {p for i, p in enumerate(params)
            if i not in fn.static_argnums and i >= fn.partial_bound}


#: attribute reads that are trace-time STATIC on a traced array —
#: exactly the exemption set docs/ANALYSIS.md documents for TL102/103
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _mentions_bare(expr, names):
    """Does `expr` reference any of `names` as a traced VALUE — a bare
    load, or an attribute/method that reads the value (`x.any()`,
    `x.sum()`)? Only the static metadata attrs (`x.shape` / `x.ndim` /
    `x.dtype` / `x.size`) are exempt: those are compile-time facts and
    must not trip the traced-value rules."""
    hits = []

    class V(ast.NodeVisitor):
        def visit_Attribute(self, node):
            if isinstance(node.value, ast.Name):
                if node.value.id in names \
                        and node.attr not in _STATIC_ATTRS:
                    hits.append(node.value.id)
                return
            self.generic_visit(node)

        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load) and node.id in names:
                hits.append(node.id)

    V().visit(expr)
    return hits


def _is_contextmanager(fn: FunctionInfo):
    """@contextlib.contextmanager functions get a TL104 pass: their
    enter/exit push/pop pairs are SYMMETRIC trace-time scoping (the
    no_grad / functional_rng idiom), not state leaking into the
    compiled graph."""
    node = fn.node
    for dec in getattr(node, "decorator_list", ()):
        name = _dotted(dec if not isinstance(dec, ast.Call)
                       else dec.func)
        if name and name.rsplit(".", 1)[-1] in (
                "contextmanager", "asynccontextmanager"):
            return True
    return False


def _memo_read_names(fn: FunctionInfo, mutation_counts):
    """Names whose mutations follow the MEMO-CACHE idiom: the function
    also READS the name (`cache.get(k)` / `k in cache` /
    `return cache[...]`) beyond the mutation sites themselves, so the
    write is an idempotent-per-key trace-time memoization (the
    _SPLASH_CACHE / kernel_config pattern), not per-call state."""
    loads = {}
    for n in _walk_own(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in mutation_counts:
            loads[n.id] = loads.get(n.id, 0) + 1
    # each mutation site itself contributes exactly one Load of the
    # base name (`X.append(...)` / `X[k] = v` both load X)
    return {name for name, c in loads.items()
            if c > mutation_counts[name]}


def check_traced_function(fn: FunctionInfo) -> Iterator[Finding]:
    """All TL1xx checks for one traced function."""
    module = fn.module
    rel = module.relpath

    def finding(rule, node, msg):
        return Finding(rule, rel, fn.qualname,
                       getattr(node, "lineno", fn.lineno), msg)

    local = _local_names(fn)
    traced = _traced_params(fn)
    cm_exempt = _is_contextmanager(fn)
    # TL107 scope: the function IS a scan/while_loop cond/body, or is
    # (transitively) called from one — a host escape here isn't one
    # frozen value at trace time, it's a per-iteration stall or an
    # outright tracer error inside the device loop
    in_loop = (fn.loop_reachable
               or fn.entry_kind in ("scan", "while_loop"))

    # pre-pass: TL104 candidate mutation counts per free name, for the
    # memo-idiom exemption
    mutation_counts = {}
    for node in _walk_own(fn):
        name = _tl104_target(node, local)
        if name:
            mutation_counts[name] = mutation_counts.get(name, 0) + 1
    memo_names = _memo_read_names(fn, mutation_counts) \
        if mutation_counts else set()

    for node in _walk_own(fn):
        # ---- TL101: host calls
        if isinstance(node, ast.Call):
            host = _is_host_call(module, node)
            if host:
                yield finding(
                    "TL101", node,
                    f"host call `{host}(...)` runs at TRACE time "
                    "(frozen into the compiled graph, or a sync): "
                    "hoist it out of the traced function")
            # ---- TL102: .item()
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                yield finding(
                    "TL102", node,
                    ".item() on a traced value is a host sync and a "
                    "tracer error under jit — return the array and "
                    "read it host-side")
            # ---- TL107: host escapes inside a device-loop body.
            # Deliberately NOT np.asarray/np.array — those have
            # legitimate trace-time static-shape uses in kernel code;
            # the loop-specific hazards are true syncs
            if in_loop:
                what = None
                if host:
                    what = f"host call `{host}(...)`"
                rname = _resolved(module, node.func)
                if rname and (rname == "jax.device_get"
                              or rname.endswith(".device_get")):
                    what = "`jax.device_get(...)`"
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("item",
                                               "block_until_ready",
                                               "copy_to_host_async") \
                        and not node.args:
                    what = f"`.{node.func.attr}()`"
                if what:
                    yield finding(
                        "TL107", node,
                        f"{what} inside a lax.scan/while_loop body "
                        "(reached from the traced graph): the loop "
                        "runs ON DEVICE — surface per-iteration "
                        "state through the carry and read it on the "
                        "host after the loop returns")
            # ---- TL102: float()/int()/bool() on traced params
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool") \
                    and len(node.args) == 1 and traced:
                hits = _mentions_bare(node.args[0], traced)
                if hits:
                    yield finding(
                        "TL102", node,
                        f"{node.func.id}() materializes traced "
                        f"argument `{hits[0]}` on the host — use jnp "
                        "casts and keep the value on device")
        # ---- TL103: python branching on traced values
        if isinstance(node, (ast.If, ast.While)) and traced:
            hits = _mentions_bare(node.test, traced)
            if hits:
                yield finding(
                    "TL103", node,
                    f"python `{type(node).__name__.lower()}` on "
                    f"traced argument `{hits[0]}` — the branch "
                    "freezes at trace time (or raises); use "
                    "jnp.where / lax.cond / lax.select")
        if isinstance(node, ast.IfExp) and traced:
            hits = _mentions_bare(node.test, traced)
            if hits:
                yield finding(
                    "TL103", node,
                    f"conditional expression on traced argument "
                    f"`{hits[0]}` — use jnp.where / lax.select")
        # ---- TL104: mutating non-local state
        if not cm_exempt:
            name = _tl104_target(node, local)
            if name and name not in memo_names:
                if isinstance(node, ast.Call):
                    what = f"`{name}.{node.func.attr}(...)` mutates"
                else:
                    what = (f"subscript/augmented assign into "
                            f"`{name}` mutates")
                yield finding(
                    "TL104", node,
                    f"{what} closure/global state inside the trace "
                    "— it runs ONCE at trace time, not per call; "
                    "return the value instead")


def _tl104_target(node, local):
    """The free (non-local) name a node mutates, or None: mutator
    method calls (`X.append(...)`) and subscript/augmented assigns
    (`X[k] = v`)."""
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS \
            and isinstance(node.func.value, ast.Name) \
            and node.func.value.id not in local \
            and node.func.value.id not in ("self", "cls"):
        return node.func.value.id
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id not in local \
                    and t.value.id not in ("self", "cls"):
                return t.value.id
    return None


# ----------------------------------------------------- call-site rules


def check_jit_call_sites(module: ModuleIndex) -> Iterator[Finding]:
    """TL105/TL106/RH203 — rules at CALLS OF jitted handles recorded
    by the call-graph pass (`h = jax.jit(f, static_argnums=...,
    donate_argnums=...)` then `h(...)`)."""
    if not module.jit_handles:
        return
    for qual, fn in list(module.functions.items()):
        if not isinstance(fn.node,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from _check_sites_in(module, fn)


def _handle_for_call(module, call):
    if isinstance(call.func, ast.Name):
        return module.jit_handles.get(call.func.id)
    if isinstance(call.func, ast.Attribute) \
            and isinstance(call.func.value, ast.Name) \
            and call.func.value.id in ("self", "cls"):
        return module.jit_handles.get(f"self.{call.func.attr}")
    return None


def _check_sites_in(module, fn) -> Iterator[Finding]:
    rel = module.relpath
    body = list(fn.node.body)
    for node in _walk_own(fn):
        if not isinstance(node, ast.Call):
            continue
        h = _handle_for_call(module, node)
        if h is None:
            continue
        if any(isinstance(a, ast.Starred) for a in node.args):
            continue                       # positions unknowable
        # ---- TL105: unhashable static args
        for i in h.static_argnums:
            if i < len(node.args) and isinstance(
                    node.args[i], (ast.List, ast.Dict, ast.Set)):
                yield Finding(
                    "TL105", rel, fn.qualname, node.lineno,
                    f"static arg {i} of `{h.target}` is a "
                    f"{type(node.args[i]).__name__.lower()} literal "
                    "— unhashable static args defeat the jit cache "
                    "(the PR 4 conv-padding-list bug): pass a tuple")
        # ---- RH203: weak-type scalar literals as traced args
        for i, a in enumerate(node.args):
            if i in h.static_argnums:
                continue
            if isinstance(a, ast.Constant) \
                    and isinstance(a.value, (int, float)) \
                    and not isinstance(a.value, bool):
                yield Finding(
                    "RH203", rel, fn.qualname, node.lineno,
                    f"bare python number `{a.value}` passed to "
                    f"jitted `{h.target}` traces as a WEAK-typed "
                    "scalar: any caller passing a concrete-dtype "
                    "value compiles a second executable — wrap in "
                    "jnp.asarray(..., dtype) or make it static")
        # ---- TL106: donated-buffer reuse
        donated = [(i, _dotted(node.args[i]))
                   for i in h.donate_argnums if i < len(node.args)]
        donated = [(i, d) for i, d in donated if d is not None]
        if donated:
            yield from _donation_reuse(rel, fn, body, node, h, donated)


def _donation_reuse(rel, fn, body, call, handle, donated):
    """Scan statements after the donating call for loads of the
    donated names (stopping per-name at rebinding)."""
    stmt = getattr(call, "_tracelint_parent", None)
    # rebinding via the call's own assignment targets clears the name
    rebound = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for sub in ast.walk(t):
                d = _dotted(sub) if isinstance(
                    sub, (ast.Name, ast.Attribute)) else None
                if d:
                    rebound.add(d)
    live = {d for _, d in donated if d not in rebound}
    if not live:
        return
    # statements strictly after the donating one, same block only
    # (best effort — nested blocks after it are included via walk)
    try:
        idx = body.index(stmt)
    except ValueError:
        return
    for later in body[idx + 1:]:
        for sub in ast.walk(later):
            if isinstance(sub, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(sub, "ctx", None),
                                   ast.Store):
                d = _dotted(sub)
                if d in live:
                    live.discard(d)
        for sub in ast.walk(later):
            if isinstance(sub, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(sub, "ctx", None),
                                   ast.Load):
                d = _dotted(sub)
                if d in live:
                    yield Finding(
                        "TL106", rel, fn.qualname, sub.lineno,
                        f"`{d}` was DONATED to `{handle.target}` "
                        f"(line {call.lineno}) and read again here — "
                        "donated buffers alias the outputs; rebind "
                        "the result or drop donate_argnums")
                    live.discard(d)
        if not live:
            return


# ------------------------------------------------- recompile-hazard pass

_SHARDING_KWARGS = ("out_shardings", "in_shardings")


def _p_literal_entries(call):
    """A `P(...)`/`PartitionSpec(...)` call -> entry list for
    `specs.literal_is_canonical`, or None if not a P-literal."""
    name = _dotted(call.func)
    if name is None or name.rsplit(".", 1)[-1] not in (
            "P", "PartitionSpec"):
        return None
    entries = []
    for a in call.args:
        if isinstance(a, ast.Constant):
            entries.append(a.value)
        elif isinstance(a, ast.Tuple) and all(
                isinstance(e, ast.Constant) for e in a.elts):
            entries.append(tuple(e.value for e in a.elts))
        else:
            entries.append(_specs.OPAQUE)
    return entries


def _canonical_wrapped(parents):
    """True when one of the enclosing calls is canonicalize_spec /
    canonical_sharding — the literal is normalized at runtime."""
    for p in parents:
        if isinstance(p, ast.Call):
            name = _dotted(p.func)
            if name and name.rsplit(".", 1)[-1] in (
                    "canonicalize_spec", "canonical_sharding"):
                return True
    return False


def check_recompile_hazards(module: ModuleIndex) -> Iterator[Finding]:
    """RH201/RH202: non-canonical P literals at JIT-BOUNDARY sharding
    positions — `out_shardings=`/`in_shardings=` kwargs and
    `NamedSharding(...)` constructor args — unless wrapped in
    canonicalize_spec/canonical_sharding. (in_specs/out_specs of
    shard_maps USED INSIDE a trace carry no cache identity, so they
    are deliberately out of scope.)"""
    rel = module.relpath
    contexts = []          # (P-call, enclosing qual, wrapping parents)

    def qual_at(node):
        best = None
        for f in module.functions.values():
            n = f.node
            if getattr(n, "lineno", 1) <= node.lineno <= getattr(
                    n, "end_lineno", getattr(n, "lineno", 1)):
                if best is None or n.lineno > best.node.lineno:
                    best = f
        return best.qualname if best else "<module>"

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            tail = name.rsplit(".", 1)[-1] if name else ""
            roots = []
            if tail == "NamedSharding" and len(node.args) >= 2:
                roots.append(node.args[1])
            for kw in node.keywords:
                if kw.arg in _SHARDING_KWARGS:
                    roots.append(kw.value)
            for root in roots:
                for sub, parents in _walk_with_parents(root):
                    if isinstance(sub, ast.Call):
                        entries = _p_literal_entries(sub)
                        if entries is not None and \
                                not _canonical_wrapped(parents):
                            contexts.append((sub, entries))
    for sub, entries in contexts:
        ok, why = _specs.literal_is_canonical(entries)
        if ok:
            continue
        rule = "RH202" if entries and all(
            e is None for e in entries) else "RH201"
        yield Finding(rule, rel, qual_at(sub), sub.lineno,
                      f"jit-boundary spec P({_fmt_entries(entries)}) "
                      f"is not canonical: {why}")


def _fmt_entries(entries):
    return ", ".join(
        "?" if e is _specs.OPAQUE else repr(e) for e in entries)


def _walk_with_parents(root):
    stack = [(root, ())]
    while stack:
        node, parents = stack.pop()
        yield node, parents
        for child in ast.iter_child_nodes(node):
            stack.append((child, parents + (node,)))
