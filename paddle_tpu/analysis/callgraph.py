"""Traced-function call-graph resolution for tracelint.

Static (AST-only — nothing is imported) discovery of which functions
in the package run UNDER A JAX TRACE, resolved outward from the trace
entries the framework actually uses:

* ``instrumented_jit(fn, name, ...)`` (`jit/functional.py`) and plain
  ``jax.jit(fn, ...)``
* ``parallel.shard_map(body, mesh=..., in_specs=..., out_specs=...)``
  (the 0.4.x compat shim) and ``jax.experimental.shard_map.shard_map``
* ``jax.lax.scan(body, ...)`` bodies

The function argument is resolved through the package's real idioms:
a bare name (module function or in-scope nested def), a method
reference (``self._fn``), a ``functools.partial(fn, ...)``, a lambda,
a local name previously bound (``body = self._step_body(cfg)``), or —
the serving-engine pattern — a CALL of a builder whose return value is
a traced function (``instrumented_jit(self._build_step(), ...)``
resolves `_build_step` -> `return self._step_body(...)` ->
`_step_body` -> ``return step`` -> the nested ``step`` def). From the
resolved entries, tracedness propagates transitively through every
call the AST can resolve inside the package: bare names in scope,
``self.method`` within the same class, and ``from`` -imported package
functions — cross-module propagation included (the mixed step's
``_ffn_dense`` / ``_ln`` helpers in `incubate/nn/fused_transformer.py`
are reached from `serving/engine.py` this way).

Unresolvable targets (attribute chains on unknown objects, dynamic
dispatch) are skipped: the analysis UNDER-approximates tracedness, so
every rule it fires inside a traced function is real with respect to
the call graph. Jit handles (``self._step_fn = instrumented_jit(...)``)
are also recorded with their ``static_argnums`` / ``donate_argnums``
so call-site rules (unhashable static args, use-after-donation) can
check the caller side.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Tuple

#: dotted-name suffixes that make a call a trace entry; value = index
#: of the traced-function argument (`lax.while_loop` traces TWO
#: arguments — cond at 0 and body at 1; `_entry_kind` returns the
#: full index tuple)
TRACE_ENTRIES = {
    "instrumented_jit": 0,
    "jax.jit": 0,
    "shard_map": 0,
    "lax.scan": 0,
    "lax.while_loop": 0,
}

#: imported-module targets that count for the bare ``shard_map`` /
#: ``lax.scan`` suffixes (a user-defined shard_map in some unrelated
#: module must not create trace roots)
_SHARD_MAP_HOMES = ("parallel", "jax.experimental.shard_map", "jax")
_SCAN_HOMES = ("jax.lax", "jax")


@dataclasses.dataclass
class FunctionInfo:
    module: "ModuleIndex"
    qualname: str
    node: ast.AST                     # FunctionDef | Lambda
    params: Tuple[str, ...]
    class_name: Optional[str] = None
    parent: Optional["FunctionInfo"] = None
    nested: Dict[str, "FunctionInfo"] = dataclasses.field(
        default_factory=dict)
    traced: bool = False
    #: True when this function is the DIRECT argument of a trace entry
    #: (its parameters are traced values); transitively-traced callees
    #: get context-free rules only
    trace_entry: bool = False
    static_argnums: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    #: leading params bound host-side by functools.partial at the
    #: trace root — NOT traced values
    partial_bound: int = 0
    #: which trace entry made it traced ("jit" | "shard_map" | "scan"
    #: | "while_loop")
    entry_kind: Optional[str] = None
    #: True when the function runs INSIDE a device loop — it is a
    #: scan/while_loop body (or cond), or transitively called from
    #: one. Host escapes here stall/fail per iteration, not per trace:
    #: TL107's scope
    loop_reachable: bool = False

    @property
    def name(self):
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def lineno(self):
        return self.node.lineno


@dataclasses.dataclass
class JitHandle:
    """A name a jitted callable was bound to (`self._step = jax.jit(f,
    donate_argnums=(0, 1))`), for caller-side rules."""
    module: "ModuleIndex"
    #: "name" for plain locals/globals, "self.attr" for attributes
    target: str
    static_argnums: Tuple[int, ...]
    donate_argnums: Tuple[int, ...]
    lineno: int


def _dotted(node):
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_tuple(node):
    """Literal int / tuple-or-list-of-int -> tuple of ints, else ()."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return ()
        return tuple(out)
    return ()


class ModuleIndex:
    """One parsed module: imports, functions (by dotted qualname),
    classes, and per-function local-binding maps."""

    def __init__(self, path, relpath, dotted_module, tree,
                 is_package=False):
        self.path = path
        self.relpath = relpath
        self.dotted = dotted_module
        self.tree = tree
        self.is_package = is_package
        #: local alias -> imported dotted target
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        #: class name -> {method name -> FunctionInfo}
        self.classes: Dict[str, Dict[str, FunctionInfo]] = {}
        self.jit_handles: Dict[str, JitHandle] = {}
        self._collect()

    # ------------------------------------------------------- collection
    def _resolve_relative(self, node):
        """Absolute dotted module for a `from ...x import y` node.
        For a plain module `pkg.mod`, level 1 is `pkg` (strip one
        segment); for a PACKAGE (`__init__.py`, whose dotted name IS
        the package), level 1 is the package itself (strip none)."""
        if not node.level:
            return node.module or ""
        base = self.dotted.split(".")
        strip = node.level - (1 if self.is_package else 0)
        if strip:
            base = base[:len(base) - strip]
        if node.module:
            base.append(node.module)
        return ".".join(base)

    def _collect(self):
        index = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.scope: List[FunctionInfo] = []
                self.cls: List[str] = []

            # imports (any scope: the repo imports inside functions)
            def visit_Import(self, node):
                for a in node.names:
                    index.imports[a.asname or a.name.split(".")[0]] = \
                        a.name

            def visit_ImportFrom(self, node):
                mod = index._resolve_relative(node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    index.imports[a.asname or a.name] = \
                        f"{mod}.{a.name}" if mod else a.name

            def _function(self, node):
                if self.scope:
                    qual = self.scope[-1].qualname + "." + node.name
                elif self.cls:
                    qual = self.cls[-1] + "." + node.name
                else:
                    qual = node.name
                a = node.args
                params = tuple(
                    p.arg for p in (a.posonlyargs + a.args))
                info = FunctionInfo(
                    module=index, qualname=qual, node=node,
                    params=params,
                    class_name=(self.cls[-1] if self.cls
                                and not self.scope else None),
                    parent=self.scope[-1] if self.scope else None)
                index.functions[qual] = info
                if info.class_name:
                    index.classes.setdefault(
                        info.class_name, {})[node.name] = info
                if self.scope:
                    self.scope[-1].nested[node.name] = info
                self.scope.append(info)
                self.generic_visit(node)
                self.scope.pop()

            visit_FunctionDef = _function
            visit_AsyncFunctionDef = _function

            def visit_ClassDef(self, node):
                if self.scope:
                    # classes inside functions: out of scope
                    return
                self.cls.append(node.name)
                self.generic_visit(node)
                self.cls.pop()

        V().visit(self.tree)

    # ------------------------------------------------------- resolution
    def resolve_alias(self, dotted_name):
        """Expand the leading alias of 'a.b.c' through this module's
        imports -> absolute dotted name (best effort)."""
        if dotted_name is None:
            return None
        head, _, rest = dotted_name.partition(".")
        target = self.imports.get(head)
        if target is None:
            return dotted_name
        return f"{target}.{rest}" if rest else target


class PackageIndex:
    """Every module under a root directory, plus cross-module lookup."""

    def __init__(self, root, package_name=None):
        self.root = os.path.abspath(root)
        base = package_name or os.path.basename(self.root.rstrip("/"))
        self.modules: Dict[str, ModuleIndex] = {}      # dotted -> index
        self.by_path: Dict[str, ModuleIndex] = {}
        self.errors: List[Tuple[str, str]] = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root)
                dotted = base + "." + rel[:-3].replace(os.sep, ".")
                is_package = dotted.endswith(".__init__")
                if is_package:
                    dotted = dotted[:-len(".__init__")]
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=path)
                except (SyntaxError, UnicodeDecodeError) as e:
                    self.errors.append((rel, str(e)))
                    continue
                mi = ModuleIndex(path, rel, dotted, tree,
                                 is_package=is_package)
                self.modules[dotted] = mi
                self.by_path[rel] = mi

    def lookup(self, dotted_fn):
        """Absolute 'pkg.mod.func' (or 'pkg.mod.Class.method') ->
        FunctionInfo, or None."""
        if not dotted_fn:
            return None
        parts = dotted_fn.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.modules.get(".".join(parts[:cut]))
            if mod is not None:
                return mod.functions.get(".".join(parts[cut:]))
        return None


# ------------------------------------------------------------ resolution


class Resolver:
    """Resolve expressions to FunctionInfos and run the traced-set
    fixpoint."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.roots: List[FunctionInfo] = []

    # -- scope utilities
    def _scope_lookup(self, name, scope: Optional[FunctionInfo],
                      module: ModuleIndex):
        """A bare name -> FunctionInfo via nested defs of enclosing
        functions, then module-level defs, then imports."""
        f = scope
        while f is not None:
            if name in f.nested:
                return f.nested[name]
            f = f.parent
        if name in module.functions:
            return module.functions[name]
        target = module.imports.get(name)
        if target:
            return self.index.lookup(target)
        return None

    def _local_binding(self, name, scope: Optional[FunctionInfo]):
        """Last single-name assignment `name = <expr>` in the scope's
        body (best effort, no flow analysis)."""
        if scope is None or not isinstance(
                scope.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        found = None
        for node in ast.walk(scope.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name:
                found = node.value
        return found

    def resolve_function_expr(self, expr, scope, module, _depth=0):
        """Expression in traced-argument position -> [FunctionInfo]."""
        if _depth > 8 or expr is None:
            return []
        if isinstance(expr, ast.Lambda):
            qual = (scope.qualname + ".<lambda>") if scope \
                else "<lambda>"
            info = module.functions.get(qual)
            if info is None:
                a = expr.args
                info = FunctionInfo(
                    module=module, qualname=qual, node=expr,
                    params=tuple(p.arg for p in
                                 (a.posonlyargs + a.args)),
                    parent=scope)
                module.functions[qual] = info
            return [info]
        if isinstance(expr, ast.Name):
            f = self._scope_lookup(expr.id, scope, module)
            if f is not None:
                return [f]
            bound = self._local_binding(expr.id, scope)
            if bound is not None and bound is not expr:
                return self.resolve_function_expr(bound, scope, module,
                                                 _depth + 1)
            return []
        if isinstance(expr, ast.Attribute):
            # self._fn / cls._fn -> method of the enclosing class
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id in ("self", "cls"):
                cls = self._enclosing_class(scope)
                if cls:
                    m = module.classes.get(cls, {}).get(expr.attr)
                    if m is not None:
                        return [m]
                return []
            f = self.index.lookup(
                module.resolve_alias(_dotted(expr)))
            return [f] if f is not None else []
        if isinstance(expr, ast.Call):
            callee = _dotted(expr.func)
            if callee is not None and \
                    module.resolve_alias(callee) is not None and \
                    module.resolve_alias(callee).endswith(
                        "functools.partial") and expr.args:
                fns = self.resolve_function_expr(
                    expr.args[0], scope, module, _depth + 1)
                for f in fns:
                    # partial-bound leading positionals are host
                    # values, not traced arguments
                    f.partial_bound = max(f.partial_bound,
                                          len(expr.args) - 1)
                return fns
            # builder call: traced fns are whatever the builder returns
            builders = self.resolve_function_expr(expr.func, scope,
                                                 module, _depth + 1)
            out = []
            for b in builders:
                out.extend(self._returned_functions(b, _depth + 1))
            return out
        return []

    def _enclosing_class(self, scope):
        f = scope
        while f is not None:
            if f.class_name:
                return f.class_name
            f = f.parent
        return None

    def _returned_functions(self, fn: FunctionInfo, _depth):
        """Functions a builder returns (resolving `return step`,
        `return self._step_body(cfg)` chains)."""
        if not isinstance(fn.node,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
            return []
        out = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                out.extend(self.resolve_function_expr(
                    node.value, fn, fn.module, _depth))
        return out

    # -------------------------------------------------- root discovery
    def _entry_kind(self, call, scope, module):
        """(kind, traced-arg index tuple) when `call` is a trace
        entry. while_loop traces both its cond (arg 0) and body
        (arg 1)."""
        name = _dotted(call.func)
        if name is None:
            return None
        resolved = module.resolve_alias(name) or name
        tail = resolved.rsplit(".", 1)[-1]
        if tail == "instrumented_jit" or resolved == "jax.jit" \
                or resolved.endswith("jax.jit"):
            return ("jit", (0,))
        if tail == "shard_map":
            if any(h in resolved for h in _SHARD_MAP_HOMES):
                return ("shard_map", (0,))
            return None
        if resolved.endswith("lax.scan") or resolved == "lax.scan":
            return ("scan", (0,))
        if resolved.endswith("lax.while_loop") \
                or resolved == "lax.while_loop":
            return ("while_loop", (0, 1))
        return None

    def find_roots(self):
        """Walk every module for trace-entry calls; mark the resolved
        traced functions and record jit handles."""
        for module in self.index.modules.values():
            for scope, call in _calls_with_scope(module):
                ek = self._entry_kind(call, scope, module)
                if ek is None:
                    continue
                kind, arg_idx = ek
                static = donate = ()
                for kw in call.keywords:
                    if kw.arg == "static_argnums":
                        static = _int_tuple(kw.value)
                    elif kw.arg == "donate_argnums":
                        donate = _int_tuple(kw.value)
                for argi in arg_idx:
                    if len(call.args) <= argi:
                        continue
                    for fn in self.resolve_function_expr(
                            call.args[argi], scope, module):
                        fn.traced = True
                        fn.trace_entry = True
                        fn.entry_kind = fn.entry_kind or kind
                        if kind in ("scan", "while_loop"):
                            fn.loop_reachable = True
                        fn.static_argnums = fn.static_argnums \
                            or static
                        fn.donate_argnums = fn.donate_argnums \
                            or donate
                        self.roots.append(fn)
                if kind == "jit":
                    self._record_handle(call, scope, module,
                                        static, donate)

    def _record_handle(self, call, scope, module, static, donate):
        """`target = jax.jit(...)` / `self.x = instrumented_jit(...)`:
        remember the bound name for caller-side rules."""
        parent = getattr(call, "_tracelint_parent", None)
        if not isinstance(parent, ast.Assign) \
                or len(parent.targets) != 1:
            return
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            target = t.id
        elif isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) \
                and t.value.id in ("self", "cls"):
            target = f"self.{t.attr}"
        else:
            return
        module.jit_handles[target] = JitHandle(
            module=module, target=target, static_argnums=static,
            donate_argnums=donate, lineno=call.lineno)

    # ------------------------------------------------------ propagation
    def propagate(self):
        """Transitive closure: calls inside traced functions mark
        their resolvable package-internal callees traced, and callees
        of scan/while_loop bodies (or anything already loop-reachable)
        additionally `loop_reachable` — a function may be revisited
        ONCE more to push a newly-gained loop flag through callees
        first discovered via a non-loop path."""
        work = [f for f in self.roots]
        seen = {id(f) for f in work}
        while work:
            fn = work.pop()
            if not isinstance(fn.node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda)):
                continue
            in_loop = (fn.loop_reachable
                       or fn.entry_kind in ("scan", "while_loop"))
            body = fn.node.body if isinstance(fn.node, ast.Lambda) \
                else fn.node
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                for callee in self.resolve_function_expr(
                        node.func, fn, fn.module):
                    # only package-internal, non-builder targets
                    if callee.module.dotted.startswith("jax"):
                        continue
                    gained_loop = in_loop and not callee.loop_reachable
                    if gained_loop:
                        callee.loop_reachable = True
                    if id(callee) in seen and not gained_loop:
                        continue
                    callee.traced = True
                    seen.add(id(callee))
                    work.append(callee)

    def traced_functions(self):
        return [f for m in self.index.modules.values()
                for f in m.functions.values() if f.traced]


def _calls_with_scope(module: ModuleIndex):
    """Yield (enclosing FunctionInfo | None, Call) for every call in
    the module, annotating each call with its parent statement (for
    assignment-target recovery)."""
    out = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.scope: List[FunctionInfo] = []
            self.cls: List[str] = []
            self.stmt = None

        def visit(self, node):
            if isinstance(node, ast.stmt):
                prev, self.stmt = self.stmt, node
                super().visit(node)
                self.stmt = prev
                return
            super().visit(node)

        def _function(self, node):
            if self.scope:
                qual = self.scope[-1].qualname + "." + node.name
            elif self.cls:
                qual = self.cls[-1] + "." + node.name
            else:
                qual = node.name
            info = module.functions.get(qual)
            if info is None:
                self.generic_visit(node)
                return
            self.scope.append(info)
            self.generic_visit(node)
            self.scope.pop()

        visit_FunctionDef = _function
        visit_AsyncFunctionDef = _function

        def visit_ClassDef(self, node):
            if self.scope:
                return
            self.cls.append(node.name)
            self.generic_visit(node)
            self.cls.pop()

        def visit_Call(self, node):
            node._tracelint_parent = self.stmt
            out.append((self.scope[-1] if self.scope else None, node))
            self.generic_visit(node)

    V().visit(module.tree)
    return out


def build_traced_set(root, package_name=None):
    """(PackageIndex, Resolver) with roots found and tracedness
    propagated — the tracelint driver's entry point."""
    index = PackageIndex(root, package_name)
    res = Resolver(index)
    res.find_roots()
    res.propagate()
    return index, res
