"""paddle_tpu.analysis — trace-discipline tooling (ISSUE 12).

Three layers, one invariant: code that runs under a jax trace obeys
the backend's idiom discipline, and the jit-cache identity of "the
ONE jitted step" never silently breaks.

* `analysis.tracelint` / `analysis.callgraph` / `analysis.rules` —
  the AST static pass (`tools/tracelint.py` CLI, tier-1-gated).
* `analysis.guards` — runtime sanitizers: transfer guard +
  compile-count watchdog (+ NaN debug), suite-wide via
  tests/conftest.py.
* `analysis.specs` — the canonical-PartitionSpec normal form shared
  by the runtime call sites (tp_engine, hybrid_gpt) and the
  recompile-hazard lint rules.

docs/ANALYSIS.md is the rule catalog + env contract.
"""
from .specs import (canonical_sharding,  # noqa: F401
                    canonicalize_spec)
from .tracelint import (load_allowlist, reconcile,  # noqa: F401
                        run_tracelint)
from . import guards  # noqa: F401
