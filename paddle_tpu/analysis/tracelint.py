"""Tracelint driver: run the call-graph + rule passes over a package
tree, reconcile against the allowlist, render human/JSON reports.

CI semantics (`tools/tracelint.py --check`, wired into tier-1 via
tests/test_static_analysis.py):

* a finding whose key is NOT in the allowlist -> **exit 1** (new
  violation: fix it, don't allowlist it);
* a key with MORE findings than its allowlisted count -> **exit 1**
  (regression against the burn-down);
* fewer findings than allowlisted -> exit 0 with a burn-down nudge
  (shrink the count — the allowlist only ever ratchets DOWN);
* every allowlist entry carries a one-line justification, rendered in
  the report so the debt stays visible.

The allowlist lives next to the CLI (tools/tracelint_allowlist.json)
and starts as small as possible — see docs/ANALYSIS.md.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List

from . import callgraph, rules


def run_tracelint(root, package_name=None) -> List[rules.Finding]:
    """All findings for the package at `root` (e.g. .../paddle_tpu),
    sorted by (path, line)."""
    index, resolver = callgraph.build_traced_set(root, package_name)
    findings: List[rules.Finding] = []
    for fn in resolver.traced_functions():
        findings.extend(rules.check_traced_function(fn))
    for module in index.modules.values():
        findings.extend(rules.check_jit_call_sites(module))
        findings.extend(rules.check_recompile_hazards(module))
    # one finding per (key, line): the same violation reached through
    # two trace roots must not double-count against the allowlist
    seen = set()
    out = []
    for f in sorted(findings,
                    key=lambda f: (f.relpath, f.lineno, f.rule)):
        k = (f.key, f.lineno)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


# ------------------------------------------------------------ allowlist


def load_allowlist(path):
    """{key: {"count": int, "reason": str}} from the JSON allowlist
    file ({} when absent)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for e in data.get("entries", []):
        out[e["key"]] = {"count": int(e.get("count", 1)),
                         "reason": e.get("reason", "")}
    return out


def reconcile(findings, allowlist):
    """Split findings into (new, allowed) and compute burn-down /
    regression state per allowlist key.

    Returns a report dict: `new` (finding dicts), `allowed`, `over`
    ({key: (count, budget)}), `burndown` ({key: (count, budget)}),
    `ok` (bool: no new findings, no over-budget keys)."""
    by_key: Dict[str, List[rules.Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    new, allowed, over, burndown = [], [], {}, {}
    for key, fs in by_key.items():
        entry = allowlist.get(key)
        if entry is None:
            new.extend(fs)
            continue
        allowed.extend(fs)
        if len(fs) > entry["count"]:
            over[key] = (len(fs), entry["count"])
        elif len(fs) < entry["count"]:
            burndown[key] = (len(fs), entry["count"])
    for key, entry in allowlist.items():
        if key not in by_key:
            burndown[key] = (0, entry["count"])
    return {
        "new": [f.to_dict() for f in new],
        "allowed": [f.to_dict() for f in allowed],
        "over": over,
        "burndown": burndown,
        "ok": not new and not over,
    }


# -------------------------------------------------------------- reports


def render_human(report, allowlist):
    lines = []
    for f in report["new"]:
        lines.append(f"{f['relpath']}:{f['lineno']}: {f['rule']} "
                     f"[{f['qualname']}] {f['message']}")
    if report["allowed"]:
        lines.append("")
        lines.append(f"allowlisted ({len(report['allowed'])}):")
        for f in report["allowed"]:
            reason = allowlist.get(
                f"{f['rule']}:{f['relpath']}:{f['qualname']}",
                {}).get("reason", "")
            lines.append(
                f"  {f['relpath']}:{f['lineno']}: {f['rule']} "
                f"[{f['qualname']}]" + (f" — {reason}" if reason
                                        else ""))
    for key, (n, budget) in sorted(report["over"].items()):
        lines.append(f"REGRESSION {key}: {n} findings > allowlisted "
                     f"{budget}")
    for key, (n, budget) in sorted(report["burndown"].items()):
        lines.append(f"burn-down {key}: {n} findings < allowlisted "
                     f"{budget} — shrink the allowlist count")
    n_new = len(report["new"])
    lines.append("")
    lines.append(
        f"tracelint: {n_new} new finding(s), "
        f"{len(report['allowed'])} allowlisted, "
        f"{len(report['over'])} over budget"
        + (" — OK" if report["ok"] else " — FAIL"))
    return "\n".join(lines)


def main(argv=None, root=None, allowlist_path=None):
    """CLI body shared with tools/tracelint.py. Exit 0 iff --check
    passes (no new findings, no over-budget keys)."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="tracelint",
        description="AST trace-discipline lint for paddle_tpu "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: exit 1 on new/over-budget findings")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--root", default=root,
                    help="package directory to lint")
    ap.add_argument("--allowlist", default=allowlist_path,
                    help="allowlist JSON path")
    args = ap.parse_args(argv)

    pkg_root = args.root
    if pkg_root is None:
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    findings = run_tracelint(pkg_root)
    allowlist = load_allowlist(args.allowlist)
    report = reconcile(findings, allowlist)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_human(report, allowlist))
    if args.check:
        return 0 if report["ok"] else 1
    return 0
