"""paddle_tpu.parallel (exposed as paddle_tpu.distributed) — the
distributed suite (SURVEY.md §2.3), TPU-native over jax.sharding +
jax.lax collectives on ICI/DCN.
"""
import jax as _jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """`jax.shard_map` compat shim.

    jax >= 0.5 exposes `jax.shard_map(..., check_vma=...)`; on the 0.4.x
    line the same machinery lives at
    `jax.experimental.shard_map.shard_map(..., check_rep=...)`. Every
    manual-collective module in this package goes through this one
    helper so the framework runs on both. Defined before the submodule
    imports below so `from . import shard_map` works during package
    init."""
    native = getattr(_jax, "shard_map", None)
    if native is not None:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _esm
    if check_vma is None or check_vma:
        if check_vma:
            kw["check_rep"] = True
        return _esm(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, **kw)
    # check_vma=False path. 0.4.x's shard_map is broken when DIFFERENTIATED
    # with the check disabled (its partial-eval/transpose machinery trips a
    # _SpecError on scalar residuals), and check_rep=True rejects the
    # lax.cond bodies these callers run — which is why they disable the
    # check in the first place. Forward-only works fine, so: wrap the
    # forward shard_map in a custom_vjp whose backward runs jax.vjp of the
    # body INSIDE a second shard_map (recompute-style), reproducing the
    # non-rewrite transpose semantics by hand — cotangents of outputs
    # replicated over unmentioned mesh axes are pre-divided by the axis
    # product, and input cotangents are psum'ed over their spec's
    # unmentioned axes. The old primitive is never transposed.
    import numpy as _np

    import jax.numpy as _jnp
    from jax.dtypes import float0 as _float0
    from jax.sharding import PartitionSpec as _P
    try:
        from jax._src.tree_util import broadcast_prefix as _bprefix
    except ImportError:  # same helper, re-exported
        from jax.experimental.shard_map import broadcast_prefix as _bprefix

    _is_spec = lambda s: isinstance(s, _P)
    axis_sizes = dict(mesh.shape)

    def _mentioned(spec):
        names = set()
        for entry in spec:
            if entry is None:
                continue
            names.update(entry if isinstance(entry, tuple) else (entry,))
        return names

    def _unmentioned_prod(spec):
        return int(_np.prod([axis_sizes[a] for a in axis_sizes
                             if a not in _mentioned(spec)] or [1]))

    def _run_fwd(*args):
        return _esm(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False, **kw)(*args)

    call = _jax.custom_vjp(_run_fwd)

    def _fwd(*args):
        return _run_fwd(*args), args

    def _bwd(args, g):
        g_flat, g_tree = _jax.tree.flatten(g)
        g_specs = _bprefix(out_specs, g, is_leaf=_is_spec)
        g_flat = [gl if gl.dtype == _float0
                  else gl / _unmentioned_prod(s)
                  for gl, s in zip(g_flat, g_specs)]
        g = _jax.tree.unflatten(g_tree, g_flat)
        a_flat, a_tree = _jax.tree.flatten(args)
        a_specs = _bprefix(in_specs, args, is_leaf=_is_spec)
        diff = [i for i, x in enumerate(a_flat)
                if _jnp.issubdtype(_jnp.result_type(x), _jnp.inexact)]

        def bwd_body(args, g):
            flat = _jax.tree.leaves(args)

            def restricted(*diff_leaves):
                full = list(flat)
                for i, leaf in zip(diff, diff_leaves):
                    full[i] = leaf
                return f(*_jax.tree.unflatten(a_tree, full))

            _, vjp_fn = _jax.vjp(restricted, *[flat[i] for i in diff])
            cts = vjp_fn(g)
            return tuple(
                _jax.lax.psum(ct, un) if (un := tuple(
                    a for a in axis_sizes
                    if a not in _mentioned(a_specs[i]))) else ct
                for ct, i in zip(cts, diff))

        bwd_sm = _esm(bwd_body, mesh=mesh,
                      in_specs=(in_specs, out_specs),
                      out_specs=tuple(a_specs[i] for i in diff),
                      check_rep=False, **kw)
        diff_cts = bwd_sm(args, g) if diff else ()
        ct_flat = [_np.zeros(_jnp.shape(x), _float0) for x in a_flat]
        for i, ct in zip(diff, diff_cts):
            ct_flat[i] = ct
        return tuple(_jax.tree.unflatten(a_tree, ct_flat))

    call.defvjp(_fwd, _bwd)
    return call


from . import env  # noqa: F401,E402
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, barrier,
    is_initialized, global_mesh,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, all_reduce, all_gather, broadcast, reduce,
    scatter, all_to_all, all_reduce_coalesced, wait,
)
from .comm_extras import (  # noqa: F401
    all_gather_object, reduce_scatter, isend, irecv, send, recv, stream,
)
from . import moe_utils as utils  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from .strategy import DistributedStrategy  # noqa: F401
from .data_parallel import DataParallel, shard_batch  # noqa: F401
from .recompute import recompute  # noqa: F401
from .auto_tuner import (  # noqa: F401
    ClusterSpec, CostModel, ModelSpec, Strategy, StrategyTuner,
    TunedResult, tune,
)
from . import fleet  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """paddle.distributed.spawn parity: under jax single-controller SPMD a
    single process drives all chips, so spawn degenerates to a direct call
    (multi-host launch is `python -m paddle_tpu.distributed.launch`)."""
    func(*args)
