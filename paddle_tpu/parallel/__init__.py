"""paddle_tpu.parallel (exposed as paddle_tpu.distributed) — the
distributed suite (SURVEY.md §2.3), TPU-native over jax.sharding +
jax.lax collectives on ICI/DCN.
"""
from . import env  # noqa: F401
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, barrier,
    is_initialized, global_mesh,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, all_reduce, all_gather, broadcast, reduce,
    scatter, all_to_all, wait,
)
from .comm_extras import (  # noqa: F401
    all_gather_object, reduce_scatter, isend, irecv, send, recv, stream,
)
from . import moe_utils as utils  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
    get_hybrid_communicate_group, set_hybrid_communicate_group,
)
from .strategy import DistributedStrategy  # noqa: F401
from .data_parallel import DataParallel, shard_batch  # noqa: F401
from .recompute import recompute  # noqa: F401
from .auto_tuner import (  # noqa: F401
    ClusterSpec, CostModel, ModelSpec, Strategy, StrategyTuner,
)
from . import fleet  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """paddle.distributed.spawn parity: under jax single-controller SPMD a
    single process drives all chips, so spawn degenerates to a direct call
    (multi-host launch is `python -m paddle_tpu.distributed.launch`)."""
    func(*args)
