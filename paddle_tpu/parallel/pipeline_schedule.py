"""Compiled pipeline schedules for arbitrary ``PipelineLayer`` models.

Parity: `python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:34`
(`PipelineParallel` 1F1B schedule) and `:464`
(`PipelineParallelWithInterleave`), which drive NCCL send/recv per
microbatch from Python. TPU-native inversion: the whole schedule — every
microbatch forward, every backward, all inter-stage transfers — compiles
into ONE XLA executable; stage-to-stage transfers are `lax.ppermute` over
the "pp" mesh axis riding ICI.

Schedules:

- ``"gpipe"``: forward-only tick scan; jax AD generates the (reverse-
  pipelined) backward. Activation stash: O(M) microbatch inputs per stage.
- ``"1f1b"`` (+ ``num_virtual_stages`` ≥ 1): explicit fwd/bwd-interleaved
  schedule with manual per-chunk `jax.vjp` (full recompute-from-stash, the
  reference's recompute_interval=1 behavior). With v virtual stages the
  model is cut into pp*v chunks and device d owns the NON-contiguous
  chunks {d, d+pp, ...} — `PipelineParallelWithInterleave` parity with a
  1/v bubble. Conflict-free tick formulas (chunk c = j*pp + d, micro
  m = g*pp + r):

      forward  at t = 2*phi,      phi  = g*pp*v + j*pp + r + d
      backward at t = 2*beta + 1, beta = (pp*v-1) + g*pp*v
                                         + (v-1-j)*pp + r + (pp-1-d)

  Consecutive chunks are exactly one phi apart so activations ride a
  one-hop ppermute ring (stored on arrival parity, consumed next tick);
  per-(tick, device) decoding is unique (r = residue mod pp, j = residue
  mod v, g = quotient). The last chunk's backward lands one tick after
  its forward — the 1F1B property.
- ``"zero_bubble"``: ZB-style split backward (Qi et al., "Zero Bubble
  Pipeline Parallelism"). F and B keep the exact 1f1b formulas above,
  but B computes ONLY the input gradient (the dx chain is the critical
  path) and each (chunk, micro)'s weight gradient runs as a separate W
  sub-tick scheduled host-side (`_zb_w_schedule`, greedy) into the
  ticks where the 1f1b decode leaves the device idle — the fill/drain
  bubble does the dw work instead of idling. Costs: one extra forward
  recompute per micro (B and W each replay the stage forward from the
  stash) and O(M)-deep activation + cotangent stashes (the deferred W
  must see its micro's input and arriving cotangent). Parity-tested
  against 1f1b/eager at the same rtol; `schedule_bubble_ticks` reports
  strictly fewer bubble ticks than 1f1b whenever pp >= 2.

Features on the 1f1b path:

- **Stage-local parameters** (``stage_local_params=True``): per-device
  FLAT param segments sharded over the pp axis (`P("pp")`) — each device
  holds 1/pp of the model inside the compiled step instead of a full
  replica (the reference's `pp_layers.py:211` partition semantics).
  Branches unflatten their chunk's params from the local segment at
  static offsets; grads accumulate into a local flat segment and come
  back sharded.
- **Train-mode buffers** (e.g. BatchNorm running stats): buffers ride the
  scan carry; each chunk's forward updates its own buffers per microbatch
  (in increasing micro order — the reference PipelineParallel updates
  per-micro too), and the final values are routed home by masking to the
  owner device and psum-ing.

Stage functions must be collective-free (tp/mp inside stages is the
flagship hybrid_gpt's job); inter-stage activations ride a single padded
buffer of the elementwise-max shape.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import autograd
from ..core import random as rng_mod
from ..core.tensor import Tensor
from ..jit.functional import bind_arrays
from ..nn.layer_base import Layer
from ..profiler import metrics as _metrics
from . import shard_map as _shard_map


def _decode_grid(pp, v, M):
    """Vectorized host-side mirror of the compiled 1f1b decode formulas
    (module doc) over the full (tick, device) grid. Returns
    (fwd_live [T, pp], bwd_live [T, pp], bwd_chunk [T, pp],
    bwd_micro [T, pp], T) — one numpy broadcast instead of the former
    O(T*pp) Python loops."""
    gM, rM = (M - 1) // pp, (M - 1) % pp
    beta_max = (pp * v - 1) + gM * pp * v + (v - 1) * pp + rM + (pp - 1)
    T = 2 * beta_max + 2
    t = np.arange(T)[:, None]
    d = np.arange(pp)[None, :]

    def decode(u, flip_j):
        r = np.mod(u, pp)
        q = (u - r) // pp
        j = np.mod(q, v)
        g = (q - j) // v
        if flip_j:
            j = v - 1 - j
        m = g * pp + r
        live = (u >= 0) & (g >= 0) & (m < M)
        return live, j * pp + d, np.clip(m, 0, M - 1)

    f_live, _, _ = decode(t // 2 - d, False)
    f_live &= t % 2 == 0
    b_live, b_c, b_m = decode(
        (t - 1) // 2 - (pp * v - 1) - (pp - 1 - d), True)
    b_live &= t % 2 == 1
    return f_live, b_live, b_c, b_m, T


def _zb_w_schedule(pp, v, M, grid=None):
    """Greedy host-side schedule for the W (weight-grad) sub-ticks of
    the zero-bubble schedule. F and B(=input-grad only) keep the exact
    1f1b decode formulas — the dx chain is the critical path — and each
    (chunk c, micro m)'s W runs on its owner device at the earliest
    WHOLLY-IDLE tick after its B sub-tick (so a tick never does two
    slots of work); leftovers drain in ticks appended past the 1f1b
    window. Returns (w_sched int32 [T_ext, pp] holding c*M + m or -1,
    T_ext). The schedule is static, so the compiled scan consumes it as
    a constant array. `grid` takes a precomputed `_decode_grid` result
    (the auto-tuner scores many candidates through here)."""
    f_live, b_live, b_c, b_m, T = grid if grid is not None \
        else _decode_grid(pp, v, M)
    idle = ~(f_live | b_live)
    per_dev = []
    for dd in range(pp):
        b_ticks = np.where(b_live[:, dd])[0]
        idle_ticks = np.concatenate(
            [np.where(idle[:, dd])[0], np.arange(T, T + v * M)])
        assigned = {}
        ptr = 0
        for bt in b_ticks:
            while idle_ticks[ptr] <= bt:
                ptr += 1
            assigned[int(idle_ticks[ptr])] = (
                int(b_c[bt, dd]) * M + int(b_m[bt, dd]))
            ptr += 1
        per_dev.append(assigned)
    T_ext = max([T] + [max(a) + 1 for a in per_dev if a])
    w = np.full((T_ext, pp), -1, np.int32)
    for dd, a in enumerate(per_dev):
        for t_, code in a.items():
            w[t_, dd] = code
    return w, T_ext


def schedule_bubble_ticks(schedule, pp, v, M):
    """Per-stage idle schedule ticks, host-side mirror of the compiled
    decode formulas (module doc): returns ([bubble_ticks_per_stage], T).
    A stage's bubble is the ticks where none of its slots decode to a
    live (chunk, microbatch) work item — the fill/drain cost the 1F1B
    interleave amortises by 1/v and the zero-bubble W sub-ticks fill.

    Units: one tick = one slot of work (a forward, an input-grad
    backward, or — zero_bubble only — a weight-grad sub-tick), so
    zero_bubble runs 3vM active ticks per stage where gpipe/1f1b run
    2vM (their backward slot does the dx AND dw work in one tick).
    Compare bubble TICKS at matched (pp, v, M), not wall seconds."""
    if schedule == "gpipe":
        T = M + pp - 1
        return [T - M] * pp, T
    if schedule == "zero_bubble":
        grid = _decode_grid(pp, v, M)
        _, T_ext = _zb_w_schedule(pp, v, M, grid=grid)
        active = (grid[0] | grid[1]).sum(axis=0) + v * M
        return [int(T_ext - a) for a in active], T_ext
    f_live, b_live, _, _, T = _decode_grid(pp, v, M)
    active = (f_live | b_live).sum(axis=0)
    return [int(T - a) for a in active], T


def _stage_param_tensors(stage_layers):
    out, seen = [], set()
    for l in stage_layers:
        if isinstance(l, Layer):
            for _, p in l.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    out.append(p)
    return out


def _stage_buffer_tensors(stage_layers):
    out, seen = [], set()
    for l in stage_layers:
        if isinstance(l, Layer):
            for _, b in l.named_buffers():
                if id(b) not in seen:
                    seen.add(id(b))
                    out.append(b)
    return out


def _make_stage_fn(stage_layers, param_tensors, buffer_tensors):
    """Pure fn (param_arrays, buffer_arrays, x_array, key) ->
    (y_array, new_buffer_arrays). Buffer mutations (BN running stats)
    are captured from the bound tensors after the forward."""

    def fn(param_arrays, buffer_arrays, x, key):
        with bind_arrays(param_tensors, list(param_arrays)), \
                bind_arrays(buffer_tensors, list(buffer_arrays)), \
                rng_mod.functional_rng(key), autograd.no_grad():
            t = Tensor(x)
            for l in stage_layers:
                t = l(t)
            new_bufs = [b._data for b in buffer_tensors]
            return t._data, new_bufs

    return fn


def _make_loss_fn(loss_layer):
    def fn(y_arr, lab_arr):
        with autograd.no_grad():
            out = loss_layer(Tensor(y_arr), Tensor(lab_arr))
        return out._data.astype(jnp.float32).reshape(())

    return fn


class CompiledPipeline:
    """Compiles (loss, grads) for a PipelineLayer over a pp-axis mesh.

    Usage:
        runner = CompiledPipeline(pipeline_layer, micro_batches=4,
                                  schedule="1f1b")
        loss = runner.train_batch(x, labels, optimizer)   # sets .grad
    """

    def __init__(self, pipeline_layer, micro_batches=1, schedule="1f1b",
                 devices=None, num_virtual_stages=1,
                 stage_local_params=False):
        if schedule not in ("gpipe", "1f1b", "zero_bubble"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.layer = pipeline_layer
        self.M = int(micro_batches)
        self.schedule = schedule
        self.v = int(num_virtual_stages)
        self.stage_local = bool(stage_local_params)
        C = pipeline_layer._num_stages
        if self.v > 1:
            if schedule == "gpipe":
                raise ValueError(
                    "num_virtual_stages>1 requires 1f1b or zero_bubble")
            if C % self.v != 0:
                raise ValueError(
                    f"num_virtual_stages ({self.v}) must divide "
                    f"num_stages ({C})")
            if self.M % (C // self.v) != 0:
                raise ValueError(
                    "interleaved 1F1B needs micro_batches divisible by "
                    f"pp ({C // self.v}) — the reference has the same "
                    "constraint")
        if self.stage_local and schedule == "gpipe":
            raise ValueError(
                "stage_local_params requires 1f1b or zero_bubble")
        self.pp = C // self.v
        self.chunks = C
        loss_layer = pipeline_layer._loss_fn
        if loss_layer is None:
            raise ValueError("PipelineLayer needs loss_fn for pipelined "
                             "training")
        self._loss_arr = _make_loss_fn(loss_layer)

        self.stage_params = []     # list[list[Tensor]] per chunk
        self.stage_buffers = []    # list[list[Tensor]] per chunk
        self._stage_layers = []
        self._stage_fns = []
        for s in range(self.chunks):
            sl = pipeline_layer.get_stage_layers(s)
            pts = _stage_param_tensors(sl)
            bts = _stage_buffer_tensors(sl)
            self.stage_params.append(pts)
            self.stage_buffers.append(bts)
            self._stage_layers.append(sl)
            self._stage_fns.append(_make_stage_fn(sl, pts, bts))

        devices = devices if devices is not None else jax.devices()
        if len(devices) < self.pp:
            raise ValueError(
                f"pipeline has {self.pp} pipeline ranks but only "
                f"{len(devices)} devices")
        self.mesh = Mesh(np.array(devices[: self.pp]), ("pp",))
        if self.stage_local:
            self._build_flat_layout()
        self._compiled = {}
        if _metrics._enabled:
            bubbles, T = schedule_bubble_ticks(self.schedule, self.pp,
                                               self.v, self.M)
            for d, b in enumerate(bubbles):
                _metrics.PIPELINE_BUBBLE_TICKS.labels(str(d)).set(b)
            _metrics.PIPELINE_BUBBLE_RATIO.set(
                sum(bubbles) / max(T * self.pp, 1))

    # ---------------------------------------------- stage-local layout

    def _build_flat_layout(self):
        """Per-device flat parameter segments: device d's segment is the
        concatenation (per dtype) of its chunks' params. Sharded over
        the pp axis each device holds ~1/pp of the model."""
        pp = self.pp
        dtypes: list[str] = []
        cursors = [dict() for _ in range(pp)]
        place = []                      # per chunk: (di, off, size, shape)
        for c in range(self.chunks):
            d = c % pp
            entries = []
            for p in self.stage_params[c]:
                dt = str(p._data.dtype)
                if dt not in dtypes:
                    dtypes.append(dt)
                di = dtypes.index(dt)
                off = cursors[d].get(di, 0)
                size = max(1, int(np.prod(p.shape)))
                entries.append((di, off, size, tuple(p.shape)))
                cursors[d][di] = off + size
            place.append(entries)
        seg = [max([cur.get(di, 0) for cur in cursors] + [1])
               for di in range(len(dtypes))]
        # pad to the 128-lane tile so the sharded buffers stay aligned
        self._flat_seg = [((s + 127) // 128) * 128 for s in seg]
        self._flat_dtypes = dtypes
        self._flat_place = place

    def _flat_params(self):
        """Assemble the [pp, seg_len] sharded param buffers from the
        current Tensor values. Pure jnp ops — the params stay on device
        (no host numpy round-trip per step); the concat order matches
        `_build_flat_layout`'s cursor order, so offsets line up."""
        pp = self.pp
        out = []
        for di, dt in enumerate(self._flat_dtypes):
            rows = []
            for d in range(pp):
                parts = []
                for c in range(d, self.chunks, pp):
                    for pi, p in enumerate(self.stage_params[c]):
                        if self._flat_place[c][pi][0] == di:
                            parts.append(p._data.ravel())
                row = jnp.concatenate(parts) if parts \
                    else jnp.zeros((0,), jnp.dtype(dt))
                rows.append(jnp.pad(
                    row, (0, self._flat_seg[di] - row.shape[0])))
            out.append(jax.device_put(
                jnp.stack(rows), NamedSharding(self.mesh, P("pp"))))
        return tuple(out)

    def _unflatten_grads(self, flat_grads):
        """Sharded grad buffers (global [pp*seg_len] — rank-1 locals
        concatenated over the pp axis) -> per-chunk grad lists (lazy
        device-side slices)."""
        bufs = [g.reshape(self.pp, s)
                for g, s in zip(flat_grads, self._flat_seg)]
        grads = []
        for c in range(self.chunks):
            d = c % self.pp
            gs = []
            for pi, p in enumerate(self.stage_params[c]):
                di, off, size, shape = self._flat_place[c][pi]
                gs.append(bufs[di][d, off:off + size].reshape(shape))
            grads.append(gs)
        return grads

    def per_device_param_bytes(self):
        """Bytes of parameters resident per device inside the compiled
        step (the stage-local memory contract: ~ total/pp)."""
        if self.stage_local:
            return sum(s * np.dtype(dt).itemsize
                       for s, dt in zip(self._flat_seg,
                                        self._flat_dtypes))
        return sum(int(np.prod(p.shape)) * p._data.dtype.itemsize
                   for pts in self.stage_params for p in pts)

    # ------------------------------------------------------------ build

    def _trace_shapes(self, x_micro_shape, x_dtype):
        """Trace per-chunk output shapes. Inter-stage activations may
        differ in size (not rank/dtype): transfers ride a single padded
        buffer of the elementwise-max shape and each chunk slices its
        expected input back out."""
        key = jax.random.PRNGKey(0)
        outs = []
        aval = jax.ShapeDtypeStruct(x_micro_shape, x_dtype)
        for s in range(self.chunks):
            parr = [jax.ShapeDtypeStruct(p.shape, p._data.dtype)
                    for p in self.stage_params[s]]
            barr = [jax.ShapeDtypeStruct(b.shape, b._data.dtype)
                    for b in self.stage_buffers[s]]
            out, _ = jax.eval_shape(self._stage_fns[s], parr, barr, aval,
                                    key)
            outs.append(out)
            aval = out
        ranks = {len(o.shape) for o in outs}
        dts = {str(o.dtype) for o in outs}
        if len(ranks) > 1 or len(dts) > 1:
            raise ValueError(
                "pipelined stages must produce activations of one rank "
                f"and dtype; traced {outs}")
        pad_shape = tuple(max(o.shape[i] for o in outs)
                          for i in range(ranks.pop()))
        return outs, pad_shape, outs[0].dtype

    def _build(self, x_shape, x_dtype, lab_shape, lab_dtype):
        pp, M, v, C = self.pp, self.M, self.v, self.chunks
        B = x_shape[0]
        assert B % M == 0, "batch must divide micro_batches"
        Bm = B // M
        xm_shape = (Bm,) + tuple(x_shape[1:])
        stage_outs, act_shape, act_dtype = self._trace_shapes(
            xm_shape, x_dtype)
        in_shapes = [xm_shape] + [o.shape for o in stage_outs[:-1]]
        stage_fns = self._stage_fns
        loss_arr = self._loss_arr
        stage_local = self.stage_local
        # chunks whose buffers must be updated in the fwd slot (train
        # mode + has buffers); eval-mode buffers are read-only
        upd_bufs = [bool(bts) and any(
            getattr(l, "training", False) for l in sl
            if isinstance(l, Layer))
            for bts, sl in zip(self.stage_buffers, self._stage_layers)]
        if stage_local:
            place = self._flat_place
        zb = self.schedule == "zero_bubble"
        if zb:
            # static W sub-tick schedule (host-greedy): the scan consumes
            # it as a constant [T_ext, pp] array
            w_sched_np, T_zb = _zb_w_schedule(pp, v, M)
            w_sched_arr = jnp.asarray(w_sched_np)

        def zeros_act():
            return jnp.zeros(act_shape, act_dtype)

        def pad_act(a):
            return jnp.pad(a, [(0, t - c)
                               for c, t in zip(a.shape, act_shape)])

        def slice_act(a, shape):
            return a[tuple(slice(0, s) for s in shape)]

        def params_of(all_params, flats_local, c):
            if not stage_local:
                return all_params[c]
            return [flats_local[di][off:off + size].reshape(shape)
                    for (di, off, size, shape) in place[c]]

        def bufs_home(all_bufs, d_idx):
            """Mask each chunk's carried buffers to the owner device and
            psum them home (non-owners still hold the initial values)."""
            out = []
            for c in range(C):
                own = d_idx == (c % pp)
                out.append([jax.lax.psum(
                    jnp.where(own, b, jnp.zeros_like(b)), "pp")
                    for b in all_bufs[c]])
            return tuple(out)

        # ---------------------------------------------------- gpipe body
        def gpipe_loss(all_params, all_bufs, data, labels, base_key):
            """Per-device fn inside shard_map. data [M,Bm,...] replicated;
            forward-only GPipe scan, AD makes the reverse pipeline.
            Returns (loss, final_buffers)."""
            stage = jax.lax.axis_index("pp")
            is_last = stage == pp - 1
            T = M + pp - 1

            def key_for(s, m):
                return jax.random.fold_in(base_key, s * 8192 + m)

            def tick(carry, t):
                x_recv, bufs, loss_sum = carry
                m_out = jnp.clip(t - (pp - 1), 0, M - 1)

                def mk_fwd(s):
                    def br():
                        m = jnp.clip(t - s, 0, M - 1)
                        if s == 0:
                            x = jax.lax.dynamic_index_in_dim(
                                data, m, keepdims=False)
                        else:
                            x = slice_act(x_recv, in_shapes[s])
                        y, nb = stage_fns[s](all_params[s], bufs[s], x,
                                             key_for(s, m))
                        new_bufs = list(bufs)
                        if upd_bufs[s]:
                            # stages run every tick (idle ticks re-run a
                            # clipped micro) — only keep buffer updates
                            # from live slots
                            live = (t >= s) & (t - s < M)
                            new_bufs[s] = [jnp.where(live, nb_, ob)
                                           for nb_, ob in zip(nb, bufs[s])]
                        return pad_act(y), tuple(new_bufs)
                    return br

                y, bufs = jax.lax.switch(stage,
                                         [mk_fwd(s) for s in range(pp)])
                lab = jax.lax.dynamic_index_in_dim(labels, m_out,
                                                   keepdims=False)
                valid = jnp.logical_and(is_last, t >= pp - 1) if pp > 1 \
                    else t >= 0
                loss_t = jax.lax.cond(
                    valid,
                    lambda: loss_arr(slice_act(y, stage_outs[-1].shape),
                                     lab),
                    lambda: jnp.zeros((), jnp.float32))
                x_next = jax.lax.ppermute(
                    y, "pp", [(i, (i + 1) % pp) for i in range(pp)]) \
                    if pp > 1 else y
                return (x_next, bufs, loss_sum + loss_t), None

            (xf, bufs, loss_sum), _ = jax.lax.scan(
                tick, (zeros_act(), all_bufs,
                       jnp.zeros((), jnp.float32)),
                jnp.arange(T))
            loss = loss_sum / M
            if pp > 1:
                loss = jax.lax.psum(
                    jnp.where(is_last, loss, 0.0), "pp")
            return loss, bufs_home(bufs, stage)

        # --------------------------------- unified 1f1b body (v >= 1)
        def f1b_loss_and_grads(all_params, flats, all_bufs, data,
                               labels, base_key):
            """Per-device fn inside shard_map (see module doc for the
            tick formulas). `all_params` replicated per-chunk lists, or
            None with `flats` = per-dtype [seg_len] local segments when
            stage_local. Returns (loss, grads, final_buffers)."""
            d_idx = jax.lax.axis_index("pp")
            # last backward: chunk 0, m = M-1
            gM, rM = (M - 1) // pp, (M - 1) % pp
            beta_max = (pp * v - 1) + gM * pp * v + (v - 1) * pp + rM \
                + (pp - 1)
            T = 2 * beta_max + 2
            if zb:
                # zero-bubble: W (weight-grad) sub-ticks may consume a
                # micro's input/cotangent long after its B, so stashes
                # hold the full micro depth — the documented ZB memory
                # trade (O(M) activations) for the smaller bubble
                T = T_zb
                Dst = M
            else:
                Dst = min(M, 4 * pp)   # stash ring (in-flight < 3*pp)

            def key_for(c, m):
                return jax.random.fold_in(base_key, c * 8192 + m)

            if stage_local:
                flats_local = tuple(f.reshape(f.shape[-1]) for f in flats)
                grads0 = tuple(jnp.zeros_like(f) for f in flats_local)
            else:
                flats_local = None
                grads0 = jax.tree.map(jnp.zeros_like, all_params)
            stash0 = jnp.zeros((v, Dst) + act_shape, act_dtype)
            cot_stash0 = jnp.zeros((v, M) + act_shape, act_dtype) \
                if zb else None

            def decode_fwd(t, d):
                u = t // 2 - d
                r = jnp.mod(u, pp)
                q = (u - r) // pp
                j = jnp.mod(q, v)
                g = (q - j) // v
                m = g * pp + r
                active = (t % 2 == 0) & (u >= 0) & (m < M) & (g >= 0)
                return active, j, jnp.clip(m, 0, M - 1)

            def decode_bwd(t, d):
                u = (t - 1) // 2 - (pp * v - 1) - (pp - 1 - d)
                r = jnp.mod(u, pp)
                q = (u - r) // pp
                jj = jnp.mod(q, v)
                g = (q - jj) // v
                j = v - 1 - jj
                m = g * pp + r
                active = (t % 2 == 1) & (u >= 0) & (m < M) & (g >= 0)
                return active, j, jnp.clip(m, 0, M - 1)

            def tick(carry, t):
                if zb:
                    (act_buf, cot_buf, act_in, cot_in, stash, cot_stash,
                     bufs, grads, loss_sum) = carry
                else:
                    (act_buf, cot_buf, act_in, cot_in, stash, bufs,
                     grads, loss_sum) = carry
                    cot_stash = None
                # fwd sends leave on even ticks -> arrive odd; cotangent
                # sends leave on odd -> arrive even
                odd = t % 2 == 1
                act_buf = jnp.where(odd, act_in, act_buf)
                cot_buf = jnp.where(~odd, cot_in, cot_buf)

                f_act, f_j, f_m = decode_fwd(t, d_idx)
                b_act, b_j, b_m = decode_bwd(t, d_idx)

                # ------------------------------------------ forward slot
                def fwd_phase():
                    def mk(c):
                        jj = c // pp

                        def br():
                            ps = params_of(all_params, flats_local, c)
                            if c == 0:
                                x = jax.lax.dynamic_index_in_dim(
                                    data, f_m, keepdims=False)
                                st = stash
                            else:
                                x = slice_act(act_buf, in_shapes[c])
                                lvl = jax.lax.dynamic_update_index_in_dim(
                                    jax.lax.dynamic_index_in_dim(
                                        stash, jj, keepdims=False),
                                    act_buf, f_m % Dst, 0)
                                st = jax.lax.dynamic_update_index_in_dim(
                                    stash, lvl, jj, 0)
                            if c == C - 1 and not upd_bufs[c]:
                                # loss+grads run in the bwd slot; no
                                # buffer updates needed -> skip compute
                                return zeros_act(), st, bufs
                            y, nb = stage_fns[c](ps, bufs[c], x,
                                                 key_for(c, f_m))
                            new_bufs = list(bufs)
                            if upd_bufs[c]:
                                new_bufs[c] = nb
                            if c == C - 1:
                                return zeros_act(), st, tuple(new_bufs)
                            return pad_act(y), st, tuple(new_bufs)
                        return br
                    cidx = f_j * pp + d_idx
                    return jax.lax.switch(cidx,
                                          [mk(c) for c in range(C)])

                y_send, stash, bufs = jax.lax.cond(
                    f_act, fwd_phase,
                    lambda: (zeros_act(), stash, bufs))

                # ----------------------------------------- backward slot
                def bwd_phase():
                    def mk(c):
                        jj = c // pp

                        def br():
                            if c == 0:
                                x = jax.lax.dynamic_index_in_dim(
                                    data, b_m, keepdims=False)
                            else:
                                x = slice_act(
                                    jax.lax.dynamic_index_in_dim(
                                        jax.lax.dynamic_index_in_dim(
                                            stash, jj, keepdims=False),
                                        b_m % Dst, keepdims=False),
                                    in_shapes[c])
                            if stage_local:
                                def run(fl, xx):
                                    ps = params_of(None, fl, c)
                                    return stage_fns[c](
                                        ps, bufs[c], xx,
                                        key_for(c, b_m))[0]
                                wrt = flats_local
                            else:
                                def run(ps, xx):
                                    return stage_fns[c](
                                        ps, bufs[c], xx,
                                        key_for(c, b_m))[0]
                                wrt = all_params[c]
                            if c == C - 1:
                                lab = jax.lax.dynamic_index_in_dim(
                                    labels, b_m, keepdims=False)

                                def f(w, xx):
                                    return loss_arr(run(w, xx), lab)

                                lval, vjp = jax.vjp(f, wrt, x)
                                dps, dx = vjp(jnp.asarray(1.0 / M,
                                                          jnp.float32))
                            else:
                                _, vjp = jax.vjp(run, wrt, x)
                                cot = slice_act(cot_buf,
                                                stage_outs[c].shape)
                                dps, dx = vjp(cot)
                                lval = jnp.zeros((), jnp.float32)
                            if stage_local:
                                new_grads = tuple(
                                    g + d for g, d in zip(grads, dps))
                            else:
                                new_grads = list(grads)
                                new_grads[c] = [g + d for g, d in
                                                zip(grads[c], dps)]
                                new_grads = tuple(new_grads)
                            if c == 0:
                                dx_send = zeros_act()
                            else:
                                dx_send = pad_act(dx.astype(act_dtype))
                            return dx_send, new_grads, lval
                        return br
                    cidx = b_j * pp + d_idx
                    return jax.lax.switch(cidx,
                                          [mk(c) for c in range(C)])

                # ------------------- zero-bubble: B = input-grad only
                def bwd_phase_zb():
                    def mk(c):
                        jj = c // pp

                        def br():
                            if c == 0:
                                x = jax.lax.dynamic_index_in_dim(
                                    data, b_m, keepdims=False)
                            else:
                                x = slice_act(
                                    jax.lax.dynamic_index_in_dim(
                                        jax.lax.dynamic_index_in_dim(
                                            stash, jj, keepdims=False),
                                        b_m % Dst, keepdims=False),
                                    in_shapes[c])
                            ps = params_of(all_params, flats_local, c)

                            def run_x(xx):
                                return stage_fns[c](ps, bufs[c], xx,
                                                    key_for(c, b_m))[0]
                            # stash the arriving cotangent: this chunk's
                            # W sub-tick replays it later
                            lvl = jax.lax.dynamic_update_index_in_dim(
                                jax.lax.dynamic_index_in_dim(
                                    cot_stash, jj, keepdims=False),
                                cot_buf, b_m, 0)
                            cst = jax.lax.dynamic_update_index_in_dim(
                                cot_stash, lvl, jj, 0)
                            if c == C - 1:
                                lab = jax.lax.dynamic_index_in_dim(
                                    labels, b_m, keepdims=False)

                                def f(xx):
                                    return loss_arr(run_x(xx), lab)

                                lval, vjp = jax.vjp(f, x)
                                dx, = vjp(jnp.asarray(1.0 / M,
                                                      jnp.float32))
                            else:
                                _, vjp = jax.vjp(run_x, x)
                                cot = slice_act(cot_buf,
                                                stage_outs[c].shape)
                                dx, = vjp(cot)
                                lval = jnp.zeros((), jnp.float32)
                            if c == 0:
                                dx_send = zeros_act()
                            else:
                                dx_send = pad_act(dx.astype(act_dtype))
                            return dx_send, cst, lval
                        return br
                    cidx = b_j * pp + d_idx
                    return jax.lax.switch(cidx,
                                          [mk(c) for c in range(C)])

                # -------------------- zero-bubble: W = weight-grad slot
                def w_phase(cst, w_c, w_m):
                    def mk(c):
                        jj = c // pp

                        def br():
                            if c == 0:
                                x = jax.lax.dynamic_index_in_dim(
                                    data, w_m, keepdims=False)
                            else:
                                x = slice_act(
                                    jax.lax.dynamic_index_in_dim(
                                        jax.lax.dynamic_index_in_dim(
                                            stash, jj, keepdims=False),
                                        w_m % Dst, keepdims=False),
                                    in_shapes[c])
                            if stage_local:
                                def run_w(fl):
                                    ps = params_of(None, fl, c)
                                    return stage_fns[c](
                                        ps, bufs[c], x,
                                        key_for(c, w_m))[0]
                                wrt = flats_local
                            else:
                                def run_w(ps):
                                    return stage_fns[c](
                                        ps, bufs[c], x,
                                        key_for(c, w_m))[0]
                                wrt = all_params[c]
                            if c == C - 1:
                                lab = jax.lax.dynamic_index_in_dim(
                                    labels, w_m, keepdims=False)

                                def f(w):
                                    return loss_arr(run_w(w), lab)

                                _, vjp = jax.vjp(f, wrt)
                                dps, = vjp(jnp.asarray(1.0 / M,
                                                       jnp.float32))
                            else:
                                _, vjp = jax.vjp(run_w, wrt)
                                cot = slice_act(
                                    jax.lax.dynamic_index_in_dim(
                                        jax.lax.dynamic_index_in_dim(
                                            cst, jj, keepdims=False),
                                        w_m, keepdims=False),
                                    stage_outs[c].shape)
                                dps, = vjp(cot)
                            if stage_local:
                                return tuple(g + d_ for g, d_ in
                                             zip(grads, dps))
                            new_grads = list(grads)
                            new_grads[c] = [g + d_ for g, d_ in
                                            zip(grads[c], dps)]
                            return tuple(new_grads)
                        return br
                    return jax.lax.switch(w_c,
                                          [mk(c) for c in range(C)])

                if zb:
                    dx_send, cot_stash, l_add = jax.lax.cond(
                        b_act, bwd_phase_zb,
                        lambda: (zeros_act(), cot_stash,
                                 jnp.zeros((), jnp.float32)))
                    wcode = w_sched_arr[t][d_idx]
                    wsafe = jnp.maximum(wcode, 0)
                    grads = jax.lax.cond(
                        wcode >= 0,
                        lambda: w_phase(cot_stash, wsafe // M,
                                        wsafe % M),
                        lambda: grads)
                else:
                    dx_send, grads, l_add = jax.lax.cond(
                        b_act, bwd_phase,
                        lambda: (zeros_act(), grads,
                                 jnp.zeros((), jnp.float32)))
                loss_sum = loss_sum + l_add

                act_next = jax.lax.ppermute(
                    y_send, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                cot_next = jax.lax.ppermute(
                    dx_send, "pp", [(i, (i - 1) % pp) for i in range(pp)])
                if zb:
                    return (act_buf, cot_buf, act_next, cot_next, stash,
                            cot_stash, bufs, grads, loss_sum), None
                return (act_buf, cot_buf, act_next, cot_next, stash,
                        bufs, grads, loss_sum), None

            if zb:
                carry0 = (zeros_act(), zeros_act(), zeros_act(),
                          zeros_act(), stash0, cot_stash0, all_bufs,
                          grads0, jnp.zeros((), jnp.float32))
                (_, _, _, _, _, _, bufs, grads, loss_sum), _ = \
                    jax.lax.scan(tick, carry0, jnp.arange(T))
            else:
                carry0 = (zeros_act(), zeros_act(), zeros_act(),
                          zeros_act(), stash0, all_bufs, grads0,
                          jnp.zeros((), jnp.float32))
                (_, _, _, _, _, bufs, grads, loss_sum), _ = jax.lax.scan(
                    tick, carry0, jnp.arange(T))
            if not stage_local:
                # each leaf is owned by exactly one device (zeros
                # elsewhere): psum broadcasts the owner's grad
                grads = jax.tree.map(lambda g: jax.lax.psum(g, "pp"),
                                     grads)
            loss = jax.lax.psum(loss_sum, "pp") / M
            return loss, grads, bufs_home(bufs, d_idx)

        rep = P()
        if self.schedule == "gpipe" or (self.schedule == "1f1b"
                                        and pp == 1 and v == 1
                                        and not stage_local):
            loss_sm = _shard_map(
                gpipe_loss, mesh=self.mesh,
                in_specs=(rep, rep, rep, rep, rep),
                out_specs=(rep, rep), check_vma=False)

            def step(all_params, all_bufs, data, labels, base_key):
                def scalar_loss(ps):
                    l, bufs = loss_sm(ps, all_bufs, data, labels,
                                      base_key)
                    return l, bufs
                (loss, bufs), grads = jax.value_and_grad(
                    scalar_loss, has_aux=True)(all_params)
                return loss, grads, bufs
        else:
            fl_spec = tuple(P("pp") for _ in range(len(
                self._flat_dtypes))) if stage_local else rep
            f1b_sm = _shard_map(
                f1b_loss_and_grads, mesh=self.mesh,
                in_specs=(rep, fl_spec, rep, rep, rep, rep),
                out_specs=(rep, fl_spec if stage_local else rep, rep),
                check_vma=False)

            def step(all_params, all_bufs, data, labels, base_key,
                     flats=()):
                return f1b_sm(all_params, flats, all_bufs, data, labels,
                              base_key)

        return jax.jit(step)

    # ------------------------------------------------------------- run

    def loss_and_grads(self, x, labels):
        """Returns (loss: float, grads: per-chunk lists of arrays).
        Train-mode buffer updates (BN running stats) are written back to
        the layer's buffer tensors."""
        if _metrics._enabled:
            t0 = time.perf_counter()
            out = self._loss_and_grads(x, labels)
            _metrics.PIPELINE_STEP_SECONDS.observe(
                time.perf_counter() - t0)
            return out
        return self._loss_and_grads(x, labels)

    def _loss_and_grads(self, x, labels):
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = labels._data if isinstance(labels, Tensor) \
            else jnp.asarray(labels)
        M = self.M
        B = x.shape[0]
        assert B % M == 0, "batch must divide micro_batches"
        Bm = B // M
        data = x.reshape((M, Bm) + tuple(x.shape[1:]))
        labs = labels.reshape((M, Bm) + tuple(labels.shape[1:]))
        sig = (data.shape, str(data.dtype), labs.shape, str(labs.dtype),
               tuple(bool(bts) and any(
                   getattr(l, "training", False) for l in sl
                   if isinstance(l, Layer))
                   for bts, sl in zip(self.stage_buffers,
                                     self._stage_layers)))
        if sig not in self._compiled:
            self._compiled[sig] = self._build(
                x.shape, x.dtype, labels.shape, labels.dtype)
        all_bufs = tuple(
            [b._data for b in bts] for bts in self.stage_buffers)
        base_key = rng_mod.next_key()
        if self.schedule == "gpipe" or (self.schedule == "1f1b"
                                        and self.pp == 1 and self.v == 1
                                        and not self.stage_local):
            all_params = tuple(
                [p._data for p in pts] for pts in self.stage_params)
            loss, grads, bufs = self._compiled[sig](
                all_params, all_bufs, data, labs, base_key)
        elif self.stage_local:
            flats = self._flat_params()
            loss, flat_grads, bufs = self._compiled[sig](
                (), all_bufs, data, labs, base_key, flats)
            grads = self._unflatten_grads(flat_grads)
        else:
            all_params = tuple(
                [p._data for p in pts] for pts in self.stage_params)
            loss, grads, bufs = self._compiled[sig](
                all_params, all_bufs, data, labs, base_key)
        # write back buffer updates (no-op when nothing trains buffers)
        for bts, new in zip(self.stage_buffers, bufs):
            for b, nb in zip(bts, new):
                b._data = nb
        return loss, grads

    def apply_grads(self, grads, scale=1.0):
        """Accumulate compiled grads into the stage parameters' .grad.
        scale: multiply in the loss scale so a GradScaler's unscale_
        round-trips (the compiled path differentiates the RAW loss)."""
        for pts, gs in zip(self.stage_params, grads):
            for p, g in zip(pts, gs):
                if scale != 1.0:
                    g = g * jnp.asarray(scale, g.dtype)
                if p.grad is None:
                    p._grad = Tensor(g, stop_gradient=True)
                else:
                    p._grad._data = p._grad._data + g

    def finish_batch(self, loss, grads, optimizer, scaler=None):
        """Epilogue shared by every pipelined caller: assign grads (scaled
        so a GradScaler's unscale_ round-trips) and step."""
        scaling = (float(scaler._scale)
                   if scaler is not None and scaler.is_enable() else 1.0)
        self.apply_grads(grads, scaling)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        return Tensor(loss)

    def train_batch(self, x, labels, optimizer, scaler=None):
        """Full pipelined step: compiled loss+grads, then eager optimizer
        step over the stage parameters (.grad assigned)."""
        loss, grads = self.loss_and_grads(x, labels)
        return self.finish_batch(loss, grads, optimizer, scaler)
