"""Compiled pipeline schedules for arbitrary ``PipelineLayer`` models.

Parity: `python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:34`
(`PipelineParallel` 1F1B schedule) and `:464`
(`PipelineParallelWithInterleave`), which drive NCCL send/recv per
microbatch from Python. TPU-native inversion: the whole schedule — every
microbatch forward, every backward, all inter-stage transfers — compiles
into ONE XLA executable; stage-to-stage transfers are `lax.ppermute` over
the "pp" mesh axis riding ICI.

Two schedules:

- ``"gpipe"``: forward-only tick scan; jax AD generates the (reverse-
  pipelined) backward. Activation stash: O(M) microbatch inputs per stage.
- ``"1f1b"``: true one-forward-one-backward steady state, written as an
  explicit fwd/bwd-interleaved schedule with manual per-stage `jax.vjp`.
  In-flight activations are bounded by O(pp) (the 1F1B memory bound):
  stage s's backward of microbatch m runs at tick ``2m + 2*pp - 1 - s``,
  only ``pp - s`` ticks after its forward at ``2m + s``, so the stash is a
  pp-deep circular buffer. Backward recomputes the stage forward from the
  stashed input (full remat, the reference's recompute_interval=1
  behavior).

Both run every stage's code on every device and select the live branch
with ``lax.switch`` on the device's pp coordinate — the single-program
SPMD equivalent of per-rank stage processes. Stage functions must be
collective-free (tp/mp inside stages is the flagship hybrid_gpt's job);
inter-stage activation shapes must match (validated at build time).

Constraints (documented, validated): parameters are replicated across the
pp mesh axis (each device computes only with its own stage's, the rest
ride along for SPMD uniformity); buffers (e.g. BN running stats) are
bound read-only.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core import autograd
from ..core import random as rng_mod
from ..core.tensor import Tensor
from ..jit.functional import bind_arrays
from ..nn.layer_base import Layer


def _stage_param_tensors(stage_layers):
    out, seen = [], set()
    for l in stage_layers:
        if isinstance(l, Layer):
            for _, p in l.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    out.append(p)
    return out


def _stage_buffer_tensors(stage_layers):
    out, seen = [], set()
    for l in stage_layers:
        if isinstance(l, Layer):
            for _, b in l.named_buffers():
                if id(b) not in seen:
                    seen.add(id(b))
                    out.append(b)
    return out


def _make_stage_fn(stage_layers, param_tensors, buffer_tensors):
    """Pure fn (param_arrays, buffer_arrays, x_array, key) -> y_array.
    Buffers are call-time inputs (read-only) so state_dict loads after
    construction are seen by the compiled executable."""

    def fn(param_arrays, buffer_arrays, x, key):
        with bind_arrays(param_tensors, list(param_arrays)), \
                bind_arrays(buffer_tensors, list(buffer_arrays)), \
                rng_mod.functional_rng(key), autograd.no_grad():
            t = Tensor(x)
            for l in stage_layers:
                t = l(t)
            return t._data

    return fn


def _make_loss_fn(loss_layer):
    def fn(y_arr, lab_arr):
        with autograd.no_grad():
            out = loss_layer(Tensor(y_arr), Tensor(lab_arr))
        return out._data.astype(jnp.float32).reshape(())

    return fn


class CompiledPipeline:
    """Compiles (loss, grads) for a PipelineLayer over a pp-axis mesh.

    Usage:
        runner = CompiledPipeline(pipeline_layer, micro_batches=4,
                                  schedule="1f1b")
        loss = runner.train_batch(x, labels, optimizer)   # sets .grad
    """

    def __init__(self, pipeline_layer, micro_batches=1, schedule="1f1b",
                 devices=None):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.layer = pipeline_layer
        self.M = int(micro_batches)
        self.schedule = schedule
        self.pp = pipeline_layer._num_stages
        loss_layer = pipeline_layer._loss_fn
        if loss_layer is None:
            raise ValueError("PipelineLayer needs loss_fn for pipelined "
                             "training")
        self._loss_arr = _make_loss_fn(loss_layer)

        self.stage_params = []     # list[list[Tensor]] per stage
        self.stage_buffers = []    # list[list[Tensor]] per stage
        self._stage_layers = []
        self._stage_fns = []
        for s in range(self.pp):
            sl = pipeline_layer.get_stage_layers(s)
            pts = _stage_param_tensors(sl)
            bts = _stage_buffer_tensors(sl)
            self.stage_params.append(pts)
            self.stage_buffers.append(bts)
            self._stage_layers.append(sl)
            self._stage_fns.append(_make_stage_fn(sl, pts, bts))
        self._check_buffer_mutation()

        devices = devices if devices is not None else jax.devices()
        if len(devices) < self.pp:
            raise ValueError(
                f"pipeline has {self.pp} stages but only {len(devices)} "
                "devices")
        self.mesh = Mesh(np.array(devices[: self.pp]), ("pp",))
        self._compiled = {}

    def _check_buffer_mutation(self):
        """Buffer MUTATION (e.g. BN running stats) inside a stage would be
        traced and discarded — refuse instead of silently freezing stats;
        PipelineParallel falls back to eager accumulation. eval()-mode
        stages (read-only buffers) are fine. Re-checked every
        loss_and_grads call: the model may be toggled train()/eval()
        after construction."""
        for sl, bts in zip(self._stage_layers, self.stage_buffers):
            if bts and any(getattr(l, "training", False) for l in sl
                           if isinstance(l, Layer)):
                raise ValueError(
                    "pipelined stages with buffers (e.g. BatchNorm "
                    "running stats) are only supported in eval() mode; "
                    "train-mode buffer updates would be lost in the "
                    "compiled schedule")

    # ------------------------------------------------------------ build

    def _trace_shapes(self, x_micro_shape, x_dtype):
        """Trace per-stage output shapes. Inter-stage activations may
        differ in size (not rank/dtype): transfers ride a single padded
        buffer of the elementwise-max shape and each stage slices its
        expected input back out."""
        key = jax.random.PRNGKey(0)
        outs = []
        aval = jax.ShapeDtypeStruct(x_micro_shape, x_dtype)
        for s in range(self.pp):
            parr = [jax.ShapeDtypeStruct(p.shape, p._data.dtype)
                    for p in self.stage_params[s]]
            barr = [jax.ShapeDtypeStruct(b.shape, b._data.dtype)
                    for b in self.stage_buffers[s]]
            out = jax.eval_shape(self._stage_fns[s], parr, barr, aval,
                                 key)
            outs.append(out)
            aval = out
        ranks = {len(o.shape) for o in outs}
        dts = {str(o.dtype) for o in outs}
        if len(ranks) > 1 or len(dts) > 1:
            raise ValueError(
                "pipelined stages must produce activations of one rank "
                f"and dtype; traced {outs}")
        pad_shape = tuple(max(o.shape[i] for o in outs)
                          for i in range(ranks.pop()))
        return outs, pad_shape, outs[0].dtype

    def _build(self, x_shape, x_dtype, lab_shape, lab_dtype):
        pp, M = self.pp, self.M
        B = x_shape[0]
        assert B % M == 0, "batch must divide micro_batches"
        Bm = B // M
        xm_shape = (Bm,) + tuple(x_shape[1:])
        stage_outs, act_shape, act_dtype = self._trace_shapes(
            xm_shape, x_dtype)
        # input shape of stage s (s>=1) = output shape of stage s-1
        in_shapes = [xm_shape] + [o.shape for o in stage_outs[:-1]]
        stage_fns = self._stage_fns
        loss_arr = self._loss_arr

        def zeros_act():
            return jnp.zeros(act_shape, act_dtype)

        def pad_act(a):
            return jnp.pad(a, [(0, t - c)
                               for c, t in zip(a.shape, act_shape)])

        def slice_act(a, shape):
            return a[tuple(slice(0, s) for s in shape)]

        # ---------------------------------------------------- gpipe body
        def gpipe_loss(all_params, all_bufs, data, labels, base_key):
            """Per-device fn inside shard_map. data [M,Bm,...] replicated;
            forward-only GPipe scan, AD makes the reverse pipeline."""
            stage = jax.lax.axis_index("pp")
            is_last = stage == pp - 1
            T = M + pp - 1

            def key_for(s, m):
                return jax.random.fold_in(base_key, s * 4096 + m)

            def tick(carry, t):
                x_recv, loss_sum = carry
                m_out = jnp.clip(t - (pp - 1), 0, M - 1)  # last-stage micro

                def mk_fwd(s):
                    def br():
                        # micro in flight at stage s on tick t
                        m = jnp.clip(t - s, 0, M - 1)
                        if s == 0:
                            x = jax.lax.dynamic_index_in_dim(
                                data, m, keepdims=False)
                        else:
                            x = slice_act(x_recv, in_shapes[s])
                        return pad_act(stage_fns[s](
                            all_params[s], all_bufs[s], x, key_for(s, m)))
                    return br

                y = jax.lax.switch(stage, [mk_fwd(s) for s in range(pp)])
                lab = jax.lax.dynamic_index_in_dim(labels, m_out,
                                                   keepdims=False)
                valid = jnp.logical_and(is_last, t >= pp - 1) if pp > 1 \
                    else t >= 0
                loss_t = jax.lax.cond(
                    valid,
                    lambda: loss_arr(slice_act(y, stage_outs[-1].shape),
                                     lab),
                    lambda: jnp.zeros((), jnp.float32))
                x_next = jax.lax.ppermute(
                    y, "pp", [(i, (i + 1) % pp) for i in range(pp)]) \
                    if pp > 1 else y
                return (x_next, loss_sum + loss_t), None

            (xf, loss_sum), _ = jax.lax.scan(
                tick, (zeros_act(), jnp.zeros((), jnp.float32)),
                jnp.arange(T))
            loss = loss_sum / M
            if pp > 1:
                loss = jax.lax.psum(
                    jnp.where(is_last, loss, 0.0), "pp")
            return loss

        # ----------------------------------------------------- 1f1b body
        def f1b_loss_and_grads(all_params, all_bufs, data, labels,
                               base_key):
            """Per-device fn inside shard_map. Returns (loss, grads) with
            grads replicated (psum over pp at the end)."""
            stage = jax.lax.axis_index("pp")
            T = 2 * (M + pp - 1)

            def key_for(s, m):
                return jax.random.fold_in(base_key, s * 4096 + m)
            stash0 = jnp.zeros((pp,) + act_shape, act_dtype)
            grads0 = jax.tree.map(jnp.zeros_like, all_params)

            def tick(carry, t):
                act_recv, cot_recv, stash, grads, loss_sum = carry

                # ---- forward slot: stage s runs micro m at t = 2m + s
                tf = t - stage
                do_f = (tf >= 0) & (tf % 2 == 0) & (tf < 2 * M)
                m_f = jnp.clip(tf // 2, 0, M - 1)

                def fwd_phase():
                    def mk(s):
                        def br():
                            if s == 0:
                                # stage0 recomputes from data in backward
                                # — no stash write (data shape differs
                                # from the activation shape)
                                x = jax.lax.dynamic_index_in_dim(
                                    data, m_f, keepdims=False)
                                y = stage_fns[0](all_params[0],
                                                 all_bufs[0], x,
                                                 key_for(0, m_f))
                                return pad_act(y), stash
                            new_stash = jax.lax.dynamic_update_index_in_dim(
                                stash, act_recv, m_f % pp, 0)
                            if s == pp - 1:
                                # last stage: loss+grad run in its bwd
                                # slot next tick; nothing to send
                                return zeros_act(), new_stash
                            x = slice_act(act_recv, in_shapes[s])
                            y = stage_fns[s](all_params[s], all_bufs[s],
                                             x, key_for(s, m_f))
                            return pad_act(y), new_stash
                        return br
                    return jax.lax.switch(stage,
                                          [mk(s) for s in range(pp)])

                y_send, stash = jax.lax.cond(
                    do_f, fwd_phase, lambda: (zeros_act(), stash))

                # ---- backward slot: stage s bwd micro m at
                #      t = 2m + 2*pp - 1 - s  (opposite parity to fwd)
                ub = t - (2 * pp - 1 - stage)
                do_b = (ub >= 0) & (ub % 2 == 0) & (ub < 2 * M)
                m_b = jnp.clip(ub // 2, 0, M - 1)

                def bwd_phase():
                    def mk(s):
                        def br():
                            if s == 0:
                                x = jax.lax.dynamic_index_in_dim(
                                    data, m_b, keepdims=False)
                            else:
                                x = slice_act(
                                    jax.lax.dynamic_index_in_dim(
                                        stash, m_b % pp, keepdims=False),
                                    in_shapes[s])
                            if s == pp - 1:
                                lab = jax.lax.dynamic_index_in_dim(
                                    labels, m_b, keepdims=False)

                                def f(ps, xx):
                                    yy = stage_fns[s](ps, all_bufs[s],
                                                      xx, key_for(s, m_b))
                                    return loss_arr(yy, lab)

                                lval, vjp = jax.vjp(f, all_params[s], x)
                                dps, dx = vjp(jnp.asarray(1.0 / M,
                                                          jnp.float32))
                            else:
                                _, vjp = jax.vjp(
                                    lambda ps, xx: stage_fns[s](
                                        ps, all_bufs[s], xx,
                                        key_for(s, m_b)),
                                    all_params[s], x)
                                cot = slice_act(cot_recv,
                                                stage_outs[s].shape)
                                dps, dx = vjp(cot)
                                lval = jnp.zeros((), jnp.float32)
                            new_grads = list(grads)
                            new_grads[s] = [g + d for g, d in
                                            zip(grads[s], dps)]
                            if s == 0:
                                dx_send = zeros_act()  # nobody upstream
                            else:
                                dx_send = pad_act(dx.astype(act_dtype))
                            return dx_send, tuple(new_grads), lval
                        return br
                    return jax.lax.switch(stage,
                                          [mk(s) for s in range(pp)])

                dx_send, grads, l_add = jax.lax.cond(
                    do_b, bwd_phase,
                    lambda: (zeros_act(), grads,
                             jnp.zeros((), jnp.float32)))
                loss_sum = loss_sum + l_add

                # ---- inter-stage transfers (every tick; inactive slots
                # carry zeros that receivers ignore)
                act_next = jax.lax.ppermute(
                    y_send, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                cot_next = jax.lax.ppermute(
                    dx_send, "pp", [(i, (i - 1) % pp) for i in range(pp)])
                return (act_next, cot_next, stash, grads, loss_sum), None

            carry0 = (zeros_act(), zeros_act(), stash0, grads0,
                      jnp.zeros((), jnp.float32))
            (_, _, _, grads, loss_sum), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T))
            # each leaf is owned by exactly one stage (zeros elsewhere):
            # psum over pp broadcasts the owner's grad to every device.
            grads = jax.tree.map(lambda g: jax.lax.psum(g, "pp"), grads)
            loss = jax.lax.psum(loss_sum, "pp") / M
            return loss, grads

        rep = P()
        if self.schedule == "gpipe" or pp == 1:
            loss_sm = jax.shard_map(
                gpipe_loss, mesh=self.mesh,
                in_specs=(rep, rep, rep, rep, rep), out_specs=rep,
                check_vma=False)

            def step(all_params, all_bufs, data, labels, base_key):
                return jax.value_and_grad(loss_sm)(
                    all_params, all_bufs, data, labels, base_key)
        else:
            f1b_sm = jax.shard_map(
                f1b_loss_and_grads, mesh=self.mesh,
                in_specs=(rep, rep, rep, rep, rep),
                out_specs=(rep, rep), check_vma=False)

            def step(all_params, all_bufs, data, labels, base_key):
                return f1b_sm(all_params, all_bufs, data, labels,
                              base_key)

        return jax.jit(step)

    # ------------------------------------------------------------- run

    def loss_and_grads(self, x, labels):
        """Returns (loss: float, grads: per-stage lists of arrays)."""
        self._check_buffer_mutation()
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        labels = labels._data if isinstance(labels, Tensor) \
            else jnp.asarray(labels)
        M = self.M
        B = x.shape[0]
        assert B % M == 0, "batch must divide micro_batches"
        Bm = B // M
        data = x.reshape((M, Bm) + tuple(x.shape[1:]))
        labs = labels.reshape((M, Bm) + tuple(labels.shape[1:]))
        sig = (data.shape, str(data.dtype), labs.shape, str(labs.dtype))
        if sig not in self._compiled:
            self._compiled[sig] = self._build(
                x.shape, x.dtype, labels.shape, labels.dtype)
        all_params = tuple(
            [p._data for p in pts] for pts in self.stage_params)
        all_bufs = tuple(
            [b._data for b in bts] for bts in self.stage_buffers)
        # advance the global RNG per step so dropout masks differ across
        # steps and honour paddle.seed (eager-path parity)
        base_key = rng_mod.next_key()
        loss, grads = self._compiled[sig](all_params, all_bufs, data,
                                          labs, base_key)
        return loss, grads

    def apply_grads(self, grads, scale=1.0):
        """Accumulate compiled grads into the stage parameters' .grad.
        scale: multiply in the loss scale so a GradScaler's unscale_
        round-trips (the compiled path differentiates the RAW loss)."""
        for pts, gs in zip(self.stage_params, grads):
            for p, g in zip(pts, gs):
                if scale != 1.0:
                    g = g * jnp.asarray(scale, g.dtype)
                if p.grad is None:
                    p._grad = Tensor(g, stop_gradient=True)
                else:
                    p._grad._data = p._grad._data + g

    def finish_batch(self, loss, grads, optimizer, scaler=None):
        """Epilogue shared by every pipelined caller: assign grads (scaled
        so a GradScaler's unscale_ round-trips) and step."""
        scaling = (float(scaler._scale)
                   if scaler is not None and scaler.is_enable() else 1.0)
        self.apply_grads(grads, scaling)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        return Tensor(loss)

    def train_batch(self, x, labels, optimizer, scaler=None):
        """Full pipelined step: compiled loss+grads, then eager optimizer
        step over the stage parameters (.grad assigned)."""
        loss, grads = self.loss_and_grads(x, labels)
        return self.finish_batch(loss, grads, optimizer, scaler)
