"""Tensor-parallel layers (GSPMD tier).

Parity: `python/paddle/distributed/fleet/layers/mpu/mp_layers.py`
(`VocabParallelEmbedding:39`, `ColumnParallelLinear:155`,
`RowParallelLinear:293`, `ParallelCrossEntropy:438`) and `mp_ops.py`
(`_c_identity`, `_mp_allreduce`).

TPU-native: instead of allocating per-rank weight shards and calling NCCL
collectives by hand, these layers hold the FULL logical weight with a
`dist_spec` PartitionSpec (weight sharded over the "mp" mesh axis) and add
`with_sharding_constraint` hints in forward. When the training step is
compiled over a mesh (Model.fit / CompiledTrainStep with a placed model,
or pjit), XLA GSPMD partitions the matmuls and inserts the identity /
all-reduce collectives the reference codes by hand. On a single chip they
degrade to plain dense layers. For the fully manual (shard_map) path used
by the flagship hybrid trainer, see parallel/hybrid_gpt.py.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layer_base import Layer
from ..nn.layers.common import Linear, Embedding
from ..nn import functional as F
from .. import ops
from ..core.tensor import Tensor
from ..core import dispatch
from . import env as dist_env
from .topology import get_hybrid_communicate_group


def _constraint(x, spec):
    """Apply a sharding constraint when tracing inside a mesh context."""
    try:
        mesh = get_hybrid_communicate_group().mesh()
        arr = x._data if isinstance(x, Tensor) else x
        if isinstance(arr, jax.core.Tracer):
            out = jax.lax.with_sharding_constraint(
                arr, NamedSharding(mesh, spec))
            if isinstance(x, Tensor):
                t = Tensor(out, stop_gradient=x.stop_gradient)
                t._grad_node, t._out_slot = x._grad_node, x._out_slot
                return t
            return out
    except Exception:
        pass
    return x


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.embedding = Embedding(num_embeddings, embedding_dim,
                                   weight_attr=weight_attr)
        self.weight = self.embedding.weight
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        return self.embedding(x)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        # reference semantics (mp_layers.py:209 `if has_bias:`): the
        # default has_bias=None means NO bias
        bias_attr = None if has_bias else False
        self.linear = Linear(in_features, out_features, weight_attr,
                             bias_attr)
        self.weight = self.linear.weight
        self.bias = self.linear.bias
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = True
        if self.bias is not None:
            self.bias.dist_spec = P("mp")
            self.bias.is_distributed = True
        self.gather_output = gather_output

    def forward(self, x):
        out = self.linear(x)
        if not self.gather_output:
            out = _constraint(
                out, P(*([None] * (out.ndim - 1) + ["mp"])))
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.linear = Linear(in_features, out_features, weight_attr,
                             None if has_bias else False)
        self.weight = self.linear.weight
        self.bias = self.linear.bias
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        self.input_is_parallel = input_is_parallel

    def forward(self, x):
        return self.linear(x)


class ParallelCrossEntropy(Layer):
    """c_softmax_with_cross_entropy parity: with GSPMD the vocab-sharded
    logits reduce inside the compiled softmax; eager falls back to the
    dense kernel."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)


class TensorParallel(Layer):
    """fleet.distributed_model wrapper for pure-mp topologies (parity:
    meta_parallel/tensor_parallel.py). Placement of mp-sharded params on
    the mesh happens here."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg or get_hybrid_communicate_group()
        place_model_on_mesh(layers, self._hcg.mesh())

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


def tp_mesh(tensor_parallel, devices=None):
    """1-D `("mp",)` mesh over `tensor_parallel` devices — the mesh the
    TP serving engine (`serving.distributed.tp_engine`) shards its
    mixed step and KV block pools over. `devices` defaults to the
    process-local `jax.devices()` (on the CPU test harness those are
    the virtual `--xla_force_host_platform_device_count` devices)."""
    import numpy as np
    from jax.sharding import Mesh
    tp = int(tensor_parallel)
    if tp < 1:
        raise ValueError(f"tensor_parallel must be >= 1, got {tp}")
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < tp:
        raise ValueError(
            f"tensor_parallel={tp} needs {tp} devices, have "
            f"{len(devices)}")
    return Mesh(np.array(devices[:tp]), ("mp",))


def shard_major_qkv(arr, tp, num_heads, head_dim):
    """Permute a fused-QKV last axis from `(3, H, Dh)` order into
    shard-major `(tp, 3, H//tp, Dh)` order, flat shape unchanged.

    The fused stack stores q/k/v concatenated along the out axis, so a
    contiguous split of that axis over `mp` would hand shard 0 all of q
    plus part of k — NOT a head split. After this permutation each
    contiguous 1/tp chunk is exactly `(3, H//tp, Dh)` — one shard's q,
    k and v head slice in the layout `_qkv` expects when its cfg says
    `num_heads = H//tp` — so a plain `P(..., "mp")` sharding of the
    flat axis IS head partitioning. Applies to `qkv_w [L, D, 3*H*Dh]`,
    `qkv_b [L, 3*H*Dh]` and the weight-only `qkv_s` scales alike."""
    import jax.numpy as jnp
    tp = int(tp)
    lead = arr.shape[:-1]
    flat = arr.shape[-1]
    if flat != 3 * num_heads * head_dim:
        raise ValueError(
            f"fused-QKV axis {flat} != 3*{num_heads}*{head_dim}")
    if num_heads % tp:
        raise ValueError(f"num_heads={num_heads} not divisible by "
                         f"tensor_parallel={tp}")
    x = arr.reshape(*lead, 3, tp, num_heads // tp, head_dim)
    x = jnp.moveaxis(x, -4, -3)          # [..., tp, 3, H_loc, Dh]
    return x.reshape(*lead, flat)


#: decoder-stack param name -> (PartitionSpec, needs shard-major QKV
#: permutation) for head-partitioned tensor-parallel serving. Column-
#: parallel weights (qkv, ffn1) shard their OUT axis; row-parallel
#: weights (attn out, ffn2) shard their IN axis and the step body
#: psums the partial products; norms, biases-after-psum and the
#: weight-only per-out-channel scales of row-parallel mats replicate.
SERVING_TP_SPECS = {
    "ln_s": (P(), False), "ln_b": (P(), False),
    "qkv_w": (P(None, None, "mp"), True),
    "qkv_b": (P(None, "mp"), True),
    "qkv_s": (P(None, "mp"), True),
    "out_w": (P(None, "mp", None), False),
    "out_b": (P(), False), "out_s": (P(), False),
    "ffn_ln_s": (P(), False), "ffn_ln_b": (P(), False),
    "ffn1_w": (P(None, None, "mp"), False),
    "ffn1_b": (P(None, "mp"), False),
    "ffn1_s": (P(None, "mp"), False),
    "ffn2_w": (P(None, "mp"), False),
    "ffn2_b": (P(), False), "ffn2_s": (P(), False),
}

#: MoE decoder stacks (FusedMultiTransformerMoe): the gate replicates
#: (every shard routes the full token set identically); the expert-
#: stacked FFN params shard their EXPERT axis over "ep" and keep the
#: dense column/row-parallel mp split WITHIN each expert. ffn2_b is
#: per-expert, so unlike the dense stack it shards over ep (added once
#: after the mp psum, exactly like the dense bias-after-psum rule).
SERVING_MOE_TP_SPECS = {
    "gate_w": (P(), False),
    "ffn1_w": (P(None, "ep", None, "mp"), False),
    "ffn1_b": (P(None, "ep", "mp"), False),
    "ffn1_s": (P(None, "ep", "mp"), False),
    "ffn2_w": (P(None, "ep", "mp", None), False),
    "ffn2_b": (P(None, "ep", None), False),
    "ffn2_s": (P(None, "ep", None), False),
}


#: multi-LoRA adapter slot tensors (serving.adapters): each hooked
#: projection's `A [L, K, d_in, r]` / `B [L, K, r, d_out]`. A of the
#: column-parallel projections (qkv, ffn1) replicates (rank axes are
#: tiny) and B shards its out axis over "mp" — qkv's B shard-major-
#: permuted exactly like qkv_w, so the delta lands each shard's own
#: head slice; A of the row-parallel projections (out, ffn2) shards
#: its IN axis so the per-shard delta is a partial sum that joins the
#: psum the step already does for the base matmul, with B replicated.
SERVING_LORA_TP_SPECS = {
    "lora_qkv_a": (P(), False),
    "lora_qkv_b": (P(None, None, None, "mp"), True),
    "lora_out_a": (P(None, None, "mp"), False),
    "lora_out_b": (P(), False),
    "lora_ffn1_a": (P(), False),
    "lora_ffn1_b": (P(None, None, None, "mp"), False),
    "lora_ffn2_a": (P(None, None, "mp"), False),
    "lora_ffn2_b": (P(), False),
}


def serving_tp_spec(name, moe=False):
    """PartitionSpec + permute flag for one decoder param (or adapter
    slot tensor) under the TP (x EP when `moe`) serving engine.
    Unknown names raise so new stack variants fail loudly instead of
    silently replicating."""
    try:
        if name in SERVING_LORA_TP_SPECS:
            return SERVING_LORA_TP_SPECS[name]
        if moe and name in SERVING_MOE_TP_SPECS:
            return SERVING_MOE_TP_SPECS[name]
        return SERVING_TP_SPECS[name]
    except KeyError:
        raise ValueError(
            f"no tensor-parallel sharding rule for decoder param "
            f"{name!r} — add it to parallel.mp_layers.SERVING_TP_SPECS")


def tp_ep_mesh(tensor_parallel, expert_parallel, devices=None):
    """2-D `("ep", "mp")` mesh for MoE serving: `expert_parallel` rows
    of `tensor_parallel` devices. Experts shard over rows, heads and
    expert-FFN columns over columns; the token set replicates."""
    import numpy as np
    from jax.sharding import Mesh
    tp, ep = int(tensor_parallel), int(expert_parallel)
    if tp < 1 or ep < 1:
        raise ValueError(
            f"tensor_parallel/expert_parallel must be >= 1, got "
            f"{tp}/{ep}")
    n = tp * ep
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise ValueError(
            f"tensor_parallel={tp} x expert_parallel={ep} needs {n} "
            f"devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]).reshape(ep, tp), ("ep", "mp"))


def place_model_on_mesh(model, mesh):
    """device_put every parameter/buffer to its dist_spec sharding
    (replicated by default) so compiled steps run SPMD over the mesh."""
    for _, p in model.named_parameters():
        spec = p.dist_spec if p.dist_spec is not None else \
            P(*([None] * p.ndim))
        p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    for _, b in model.named_buffers():
        if isinstance(b, Tensor):
            spec = b.dist_spec if b.dist_spec is not None else \
                P(*([None] * b.ndim))
            b._data = jax.device_put(b._data, NamedSharding(mesh, spec))
    return model
