"""Eager communication API tail: object collectives, p2p tasks,
reduce_scatter, and the per-op `stream` namespace.

Parity: `python/paddle/distributed/collective.py` (`all_gather_object`
:1052, `isend` :1622, `irecv` :1672, `reduce_scatter` :1858) and
`distributed/communication/stream/`. TPU-native: object collectives ride
the tensor all_gather (pickle -> uint8 tensor); p2p rides the
jax.distributed coordination-service KV store (the same channel the
reference's TCPStore provides); reduce_scatter composes
all_reduce + local slice (one fused XLA collective when compiled).
"""
from __future__ import annotations

import pickle

import numpy as np

from ..core.tensor import Tensor
from . import collective as C
from . import env as dist_env


def _as_arr(t):
    return np.asarray(t._data if isinstance(t, Tensor) else t)


def all_gather_object(object_list, obj, group=None):
    """Gather arbitrary picklable python objects from every rank."""
    blob = np.frombuffer(pickle.dumps(obj), np.uint8)
    # 1) agree on the max length, 2) gather padded payloads + lengths
    ln = Tensor(np.array([blob.size], np.int64))
    lens = []
    C.all_gather(lens, ln, group=group)
    lens = [int(_as_arr(v)[0]) for v in lens]
    m = max(lens + [1])
    payload = Tensor(np.pad(blob, (0, m - blob.size)))
    outs = []
    C.all_gather(outs, payload, group=group)
    del object_list[:]
    for v, k in zip(outs, lens):
        object_list.append(pickle.loads(_as_arr(v)[:k].tobytes()))
    return object_list


def reduce_scatter(tensor, tensor_list, op=None, group=None,
                   sync_op=True):
    """Reduce a list of per-rank tensors, keep this rank's shard.

    Single controller (one process): the cross-rank reduction is an
    identity on the replicated per-shard values — the result is simply
    `tensor_list[rank]`, sliced DIRECTLY. Routing the concatenated
    list through `all_reduce` instead would trip its per-rank
    leading-axis heuristic whenever the concat's dim0 happens to equal
    the rank count — e.g. nranks shards of shape [1, d] concatenate to
    [nranks, d] and get summed away (ADVICE r5).

    Multi-process (jax.distributed eager mode): concat -> real
    all_reduce -> slice this rank's shard (GSPMD fuses the pair into
    one reduce-scatter when this runs inside a compiled step)."""
    op = op if op is not None else C.ReduceOp.SUM
    import jax.numpy as jnp
    rank = dist_env.get_rank()
    if not C._multiproc():
        if not (0 <= rank < len(tensor_list)):
            raise ValueError(
                f"reduce_scatter needs one input shard per rank; got "
                f"{len(tensor_list)} shards for rank {rank}")
        tensor._data = jnp.asarray(_as_arr(tensor_list[rank]))
        return tensor
    stacked = Tensor(jnp.concatenate(
        [jnp.asarray(_as_arr(t)) for t in tensor_list], axis=0))
    C.all_reduce(stacked, op=op, group=group)
    shard = _as_arr(tensor_list[0]).shape[0]
    tensor._data = jnp.asarray(
        _as_arr(stacked)[rank * shard:(rank + 1) * shard])
    return tensor


class _P2PTask:
    def __init__(self, fn=None):
        self._fn = fn
        self._done = fn is None

    def wait(self):
        if not self._done:
            self._fn()
            self._done = True
        return True

    def is_completed(self):
        return self._done


_P2P_SEQ = {}


def _kv_client():
    from jax._src import distributed as _jd
    client = getattr(_jd.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "isend/irecv need jax.distributed (init_parallel_env with "
            "PADDLE_TRAINERS>1) — the coordination-service KV store is "
            "the p2p transport")
    return client


def isend(tensor, dst, group=None):
    """Async send via the coordination-service KV store. Returns a task
    (completed eagerly: KV puts don't block on the receiver)."""
    src = dist_env.get_rank()
    seq = _P2P_SEQ.setdefault(("s", src, dst), [0])
    key = f"paddle_p2p/{src}/{dst}/{seq[0]}"
    seq[0] += 1
    arr = _as_arr(tensor)
    _kv_client().key_value_set_bytes(
        key, pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes())))
    return _P2PTask()


def irecv(tensor, src=None, group=None):
    """Async recv: task.wait() blocks on the matching isend key.
    `src` must name a concrete rank (the KV keys are (src, dst)-scoped;
    any-source receive has no transport here)."""
    if src is None:
        raise ValueError(
            "irecv requires a concrete src rank on the KV-store "
            "transport (any-source recv is unsupported)")
    dst = dist_env.get_rank()
    seq = _P2P_SEQ.setdefault(("r", src, dst), [0])
    key = f"paddle_p2p/{src}/{dst}/{seq[0]}"
    seq[0] += 1

    def fetch():
        blob = _kv_client().blocking_key_value_get_bytes(key, 60_000)
        dt, shape, raw = pickle.loads(blob)
        import jax.numpy as jnp
        tensor._data = jnp.asarray(
            np.frombuffer(raw, np.dtype(dt)).reshape(shape))
    return _P2PTask(fetch)


def send(tensor, dst=0, group=None, sync_op=True):
    return isend(tensor, dst, group).wait()


def recv(tensor, src=0, group=None, sync_op=True):
    return irecv(tensor, src, group).wait()


class _StreamNamespace:
    """`paddle.distributed.stream.*` — per-op stream variants. XLA owns
    streams on TPU; these are the sync collectives with the stream
    arguments accepted for API parity."""

    @staticmethod
    def all_reduce(tensor, op=None, group=None, sync_op=True,
                   use_calc_stream=False):
        return C.all_reduce(tensor, op=op if op is not None
                            else C.ReduceOp.SUM, group=group)

    @staticmethod
    def all_gather(tensor_or_list, tensor, group=None, sync_op=True,
                   use_calc_stream=False):
        return C.all_gather(tensor_or_list, tensor, group=group)

    @staticmethod
    def broadcast(tensor, src=0, group=None, sync_op=True,
                  use_calc_stream=False):
        return C.broadcast(tensor, src=src, group=group)

    @staticmethod
    def reduce(tensor, dst=0, op=None, group=None, sync_op=True,
               use_calc_stream=False):
        return C.reduce(tensor, dst=dst, op=op if op is not None
                        else C.ReduceOp.SUM, group=group)

    @staticmethod
    def scatter(tensor, tensor_list=None, src=0, group=None,
                sync_op=True, use_calc_stream=False):
        return C.scatter(tensor, tensor_list=tensor_list, src=src,
                         group=group)

    @staticmethod
    def reduce_scatter(tensor, tensor_list, op=None, group=None,
                       sync_op=True, use_calc_stream=False):
        return reduce_scatter(tensor, tensor_list, op=op, group=group)

    @staticmethod
    def send(tensor, dst=0, group=None, sync_op=True,
             use_calc_stream=False):
        return send(tensor, dst=dst, group=group)

    @staticmethod
    def recv(tensor, src=0, group=None, sync_op=True,
             use_calc_stream=False):
        return recv(tensor, src=src, group=group)


stream = _StreamNamespace()
