"""`paddle.distributed.fleet.utils` parity
(`python/paddle/distributed/fleet/utils/`): filesystem tools (fs.py
LocalFS/HDFSClient), log_util, and the hybrid-parallel gradient sync
helper (hybrid_parallel_util.py fused_allreduce_gradients)."""
from __future__ import annotations

import logging
import os
import shutil
import subprocess


# --------------------------------------------------------------- fs.py


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """`fs.py:120 LocalFS` — the full local toolset."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for n in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, n))
             else files).append(n)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        open(fs_path, "a").close()

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path) and not overwrite:
            raise FSFileExistsError(dst_path)
        shutil.move(src_path, dst_path)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.unlink(fs_path)

    def need_upload_download(self):
        return False

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """`fs.py HDFSClient` — shells out to the hadoop CLI exactly like
    the reference; raises up front if no hadoop binary is reachable."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop")
        if not os.path.exists(self._hadoop):
            raise RuntimeError(f"hadoop binary not found: {self._hadoop}")
        self._timeout_s = time_out / 1000.0
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]

    def _run(self, *args, check=False):
        out = subprocess.run([self._hadoop, "fs", *self._cfg, *args],
                             capture_output=True, text=True,
                             timeout=self._timeout_s)
        if check and out.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed rc={out.returncode}: "
                f"{out.stderr.strip()[:500]}")
        return out.returncode, out.stdout

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path)[0] == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path)[0] == 0

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def ls_dir(self, fs_path):
        rc, out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path, check=True)

    def delete(self, fs_path):
        self._run("-rm", "-r", fs_path, check=True)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path, check=True)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path, check=True)

    def need_upload_download(self):
        return True


# ----------------------------------------------------------- log_util


logger = logging.getLogger("paddle_tpu.distributed.fleet")


def set_log_level(level):
    """Attach the stream handler lazily (libraries must not mutate
    global logging state at import; without basicConfig the root
    lastResort handler still prints warnings+)."""
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(message)s"))
        logger.addHandler(h)
        logger.propagate = False
    logger.setLevel(level)


# ------------------------------------------- hybrid_parallel_util.py


def build_grad_buckets(pairs, bucket_size):
    """Group (param, grad) pairs into per-dtype buckets of at most
    `bucket_size` payload bytes (a single grad larger than the bucket
    gets a bucket of its own). Order within a dtype is preserved —
    callers pass parameters in reverse-creation order so the first
    buckets hold the grads the backward pass finishes first."""
    by_dtype = {}
    for p, g in pairs:
        by_dtype.setdefault(str(g._data.dtype), []).append((p, g))
    buckets = []
    cap = max(int(bucket_size or 1), 1)
    for items in by_dtype.values():
        cur, cur_bytes = [], 0
        for p, g in items:
            nbytes = int(g._data.size) * g._data.dtype.itemsize
            if cur and cur_bytes + nbytes > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append((p, g))
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
    return buckets


def fused_allreduce_gradients(parameter_list, hcg=None,
                              bucket_size=128 * 1024 * 1024,
                              scale=None):
    """`hybrid_parallel_util.py:191` parity: all-reduce every
    parameter's grad across the data-parallel world, FUSED into
    per-dtype flat buckets of at most `bucket_size` bytes — one
    collective per bucket instead of one per parameter (the
    EagerReducer bucketing the old implementation silently skipped).

    Under the single controller, grads on replicated params are already
    the GLOBAL sum (GSPMD inserts the psum inside the compiled step),
    so the device-world reduction is an identity — collective.
    all_reduce's per-rank-leading-axis heuristic must NOT run here (a
    grad whose dim0 happens to equal the device count would be summed
    away). Cross-PROCESS reduction (jax.distributed multi-host eager
    mode) still applies, and there `scale` defaults to the
    data-parallel world size: the reference's
    `_apply_collective_grads` divides the summed gradients by nranks
    (an unscaled sum would step with grads nranks(x) too large).

    The win on the 0.4.x eager multi-process path is the COLLECTIVE
    COUNT (n buckets instead of n params — each eager all_reduce is a
    synchronous host round-trip through jax.device_get, so fewer
    round-trips is the whole game; true wire/compute overlap is the
    compiled path's job, `hybrid_gpt grad_bucket_bytes`). Buckets are
    built in reverse-parameter order so the first one reduced is the
    first whose grads the backward finished."""
    import jax
    from ..core.tensor import Tensor
    from ..profiler import metrics as _metrics
    from . import collective as C
    multi_process = jax.process_count() > 1
    if scale is None and multi_process:
        if hcg is not None:
            scale = hcg.get_data_parallel_world_size()
        else:
            scale = jax.process_count()
        scale = float(scale) if scale and scale > 1 else None
    pairs = [(p, p.grad) for p in parameter_list
             if getattr(p, "grad", None) is not None]
    buckets = build_grad_buckets(list(reversed(pairs)), bucket_size)
    if _metrics._enabled:
        _metrics.GRAD_BUCKETS.labels("eager").set(len(buckets))
    for bucket in buckets:
        if multi_process:
            # ONE wire collective per bucket, reduced in place
            if len(bucket) == 1:
                C.all_reduce(bucket[0][1])
            else:
                C.all_reduce_coalesced([g for _, g in bucket])
        for p, g in bucket:
            if scale is not None:
                g = Tensor(g._data / scale)
            p.grad = g
