"""`paddle.distributed.fleet.utils` parity
(`python/paddle/distributed/fleet/utils/`): filesystem tools (fs.py
LocalFS/HDFSClient), log_util, and the hybrid-parallel gradient sync
helper (hybrid_parallel_util.py fused_allreduce_gradients)."""
from __future__ import annotations

import logging
import os
import shutil
import subprocess


# --------------------------------------------------------------- fs.py


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """`fs.py:120 LocalFS` — the full local toolset."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for n in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, n))
             else files).append(n)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        open(fs_path, "a").close()

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path) and not overwrite:
            raise FSFileExistsError(dst_path)
        shutil.move(src_path, dst_path)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.unlink(fs_path)

    def need_upload_download(self):
        return False

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """`fs.py HDFSClient` — shells out to the hadoop CLI exactly like
    the reference; raises up front if no hadoop binary is reachable."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop")
        if not os.path.exists(self._hadoop):
            raise RuntimeError(f"hadoop binary not found: {self._hadoop}")
        self._timeout_s = time_out / 1000.0
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]

    def _run(self, *args, check=False):
        out = subprocess.run([self._hadoop, "fs", *self._cfg, *args],
                             capture_output=True, text=True,
                             timeout=self._timeout_s)
        if check and out.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed rc={out.returncode}: "
                f"{out.stderr.strip()[:500]}")
        return out.returncode, out.stdout

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path)[0] == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path)[0] == 0

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def ls_dir(self, fs_path):
        rc, out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path, check=True)

    def delete(self, fs_path):
        self._run("-rm", "-r", fs_path, check=True)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path, check=True)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path, check=True)

    def need_upload_download(self):
        return True


# ----------------------------------------------------------- log_util


logger = logging.getLogger("paddle_tpu.distributed.fleet")


def set_log_level(level):
    """Attach the stream handler lazily (libraries must not mutate
    global logging state at import; without basicConfig the root
    lastResort handler still prints warnings+)."""
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(message)s"))
        logger.addHandler(h)
        logger.propagate = False
    logger.setLevel(level)


# ------------------------------------------- hybrid_parallel_util.py


def fused_allreduce_gradients(parameter_list, hcg=None,
                              bucket_size=128 * 1024 * 1024,
                              scale=None):
    """`hybrid_parallel_util.py:191` parity: all-reduce every
    parameter's grad across the data-parallel world.

    Under the single controller, grads on replicated params are already
    the GLOBAL sum (GSPMD inserts the psum inside the compiled step),
    so the device-world reduction is an identity — collective.
    all_reduce's per-rank-leading-axis heuristic must NOT run here (a
    grad whose dim0 happens to equal the device count would be summed
    away). Cross-PROCESS reduction (jax.distributed multi-host eager
    mode) still applies, and there `scale` defaults to the
    data-parallel world size: the reference's
    `_apply_collective_grads` divides the summed gradients by nranks
    (an unscaled sum would step with grads nranks(x) too large)."""
    import jax
    from ..core.tensor import Tensor
    from . import collective as C
    multi_process = jax.process_count() > 1
    if scale is None and multi_process:
        if hcg is not None:
            scale = hcg.get_data_parallel_world_size()
        else:
            scale = jax.process_count()
        scale = float(scale) if scale and scale > 1 else None
    for p in parameter_list:
        g = getattr(p, "grad", None)
        if g is None:
            continue
        if scale is not None:
            g = Tensor(g._data / scale)
        if multi_process:
            C.all_reduce(g)
        p.grad = g
