"""Auto-parallel cost model + parallel-strategy tuner.

Parity: `python/paddle/distributed/auto_parallel/cost_model.py` (comp/comm
cost graph simulation) and `auto_parallel/tuner/` (parallel-strategy
search). TPU-native re-design: instead of simulating a serialized Program
op-graph, the model prices a transformer-family training step analytically
from the hardware roofline —

  comp  = step FLOPs / (MXU peak x efficiency), stretched by the ACTUAL
          schedule's bubble fraction (tick decode via
          `pipeline_schedule.schedule_bubble_ticks`, so gpipe / 1f1b /
          zero_bubble price differently; zero_bubble additionally pays
          its extra forward recompute)
  comm  = bytes moved per collective / ICI bandwidth (ring allreduce =
          2 (n-1)/n x bytes, all_gather/reduce_scatter = (n-1)/n x bytes)
          + a per-collective dispatch latency, so the dp grad sync is
          priced per BUCKET: bucket_size=0 models the per-parameter
          eager path (n_param_tensors collectives), bucket_size>0 models
          the fused path, whose reductions overlap the backward except
          for the tail bucket
  mem   = params + grads + optimizer state (/ zero shard factor)
          + activations (/ pp mp, x remat factor; zero_bubble holds its
          O(M) act+cotangent stashes); configs over the HBM budget are
          infeasible

and the tuner brute-force scores every (dp, mp, pp, zero, micro,
schedule, bucket_size) mesh factorization — the search space is tiny
(divisors of n_devices x a few schedules/buckets), so beam search is
unnecessary on TPU pods.

`tune()` is the measurement-driven entry (the "Integrated Hardware
Architecture and Device Placement Search" direction, PAPERS.md): feed it
a short profiled run's numbers (PR 1 metrics registry: step seconds or
measured MFU, eager collective bytes/seconds) and it calibrates the
cluster's `mxu_efficiency` / `ici_bw` terms before searching, then
reports the chosen config WITH its predicted MFU so the prediction can
be checked against the next measurement (bench.py records both).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional


@dataclasses.dataclass
class ClusterSpec:
    """One TPU slice. Defaults are v5e-ish."""
    n_devices: int = 8
    peak_flops: float = 197e12       # bf16 per chip
    hbm_bytes: float = 16e9
    ici_bw: float = 9e10             # bytes/s per direction per link
    dcn_bw: float = 2.5e10
    mxu_efficiency: float = 0.4      # achievable fraction of peak
    collective_latency: float = 2e-5  # dispatch+setup per collective


@dataclasses.dataclass
class ModelSpec:
    """Transformer-family training job description."""
    n_layers: int
    d_model: int
    seq_len: int
    vocab_size: int
    d_ff: int = 0
    global_batch: int = 32
    n_heads: int = 0                 # 0 = no head-divisibility constraint
    param_bytes: int = 2             # bf16 params
    grad_bytes: int = 4
    opt_state_bytes: int = 8         # Adam m+v fp32... per param elem
    master_bytes: int = 4            # fp32 master copy
    act_bytes: int = 2
    remat: bool = True
    # MoE (ISSUE 10): E experts replace the dense FFN; each token
    # computes top_k of them, the fixed [E, C, d] dispatch buffers pad
    # compute up to capacity_factor, and the ep mesh axis shards the
    # expert params + rides the dispatch/combine all_to_all
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    def __post_init__(self):
        if self.d_ff == 0:
            self.d_ff = 4 * self.d_model

    @property
    def expert_param_elems(self) -> int:
        """Parameter elements sharded over the ep axis (the stacked
        expert FFNs); 0 for dense models."""
        if not self.moe_experts:
            return 0
        return 2 * self.d_model * self.d_ff * self.moe_experts \
            * self.n_layers

    @property
    def n_params(self) -> int:
        d, L = self.d_model, self.n_layers
        shared = 4 * d * d * L + self.vocab_size * d + self.seq_len * d
        if self.moe_experts:
            return shared + d * self.moe_experts * L \
                + self.expert_param_elems
        return shared + 2 * d * self.d_ff * L

    @property
    def active_params(self) -> int:
        """Parameters each token actually multiplies (the MFU
        numerator base): top_k experts for MoE, everything for
        dense."""
        if not self.moe_experts:
            return self.n_params
        d, L = self.d_model, self.n_layers
        return (4 * d * d + d * self.moe_experts
                + self.moe_top_k * 2 * d * self.d_ff) * L \
            + self.vocab_size * d + self.seq_len * d

    @property
    def n_param_tensors(self) -> int:
        """Parameter-tensor count estimate (12 per block + embeddings/
        final LN/head): the collective count of an UNbucketed
        per-parameter grad reduction."""
        return (13 if self.moe_experts else 12) * self.n_layers + 4

    def step_flops(self) -> float:
        """fwd+bwd (+recompute) matmul FLOPs for one global batch —
        the COMPUTED flops: MoE pays for every capacity slot (E * C =
        ~capacity_factor * top_k * T), not just the routed tokens."""
        toks = self.global_batch * self.seq_len
        base = self.useful_flops()
        if self.moe_experts:
            # E * C slots are computed vs top_k routed per token:
            # (cap_factor - 1) * top_k extra slot-equivalents each
            pad = max((self.moe_capacity_factor - 1.0)
                      * self.moe_top_k, 0.0)
            base += 6.0 * 2 * self.d_model * self.d_ff \
                * self.n_layers * pad * toks
        if self.remat:
            base *= 4.0 / 3.0  # one extra forward
        return base

    def useful_flops(self) -> float:
        """Model FLOPs for one global batch WITHOUT recompute or
        capacity-padding overhead — the MFU numerator (same
        6N_active + 6*L*S*d per-token convention as bench.py)."""
        toks = self.global_batch * self.seq_len
        return (6.0 * self.active_params
                + 6.0 * self.n_layers * self.seq_len * self.d_model) \
            * toks


@dataclasses.dataclass
class Strategy:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    ep: int = 1                      # expert parallel (MoE only)
    micro_batches: int = 1
    zero_stage: int = 0
    schedule: str = "1f1b"           # gpipe | 1f1b | zero_bubble
    virtual_stages: int = 1
    bucket_size: int = 0             # 0 = per-parameter grad reduction

    def degree(self):
        return self.dp * self.mp * self.pp * self.ep

    def as_hybrid_configs(self):
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "ep_degree": self.ep,
                "sharding_degree": 1,
                "micro_batches": self.micro_batches,
                "zero_stage": self.zero_stage,
                "schedule": self.schedule,
                "virtual_stages": self.virtual_stages,
                "bucket_size": self.bucket_size}


def _ring_allreduce_time(bytes_, n, bw):
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return 2.0 * (n - 1) / n * bytes_ / bw


def _shard_xfer_time(bytes_, n, bw):
    """all_gather or reduce_scatter of a full buffer over n ranks."""
    if n <= 1 or bytes_ <= 0:
        return 0.0
    return (n - 1) / n * bytes_ / bw


# fraction of the compute step a bucketed+overlapped dp reduction can
# hide behind (the backward half of fwd+bwd issues buckets as layers
# retire); the tail bucket is always exposed
_OVERLAP_WINDOW = 0.5


class CostModel:
    """Analytic step-time + memory estimate for a (model, strategy) pair."""

    def __init__(self, cluster: Optional[ClusterSpec] = None):
        self.cluster = cluster or ClusterSpec()

    # -------------------------------------------------------------- mem
    def memory_per_device(self, m: ModelSpec, s: Strategy) -> float:
        # params + grads live sharded over mp and pp always; the
        # expert-stacked FFN params additionally shard over ep
        shard = s.mp * s.pp
        P_eff = float(m.n_params - m.expert_param_elems) \
            + float(m.expert_param_elems) / max(s.ep, 1)
        P = P_eff
        p_bytes = P * m.param_bytes / shard
        g_bytes = P * m.grad_bytes / shard
        # optimizer state (+master weights): zero>=1 additionally shards
        # over dp; zero>=2 shards grads; zero>=3 shards params too
        opt_shard = shard * (s.dp if s.zero_stage >= 1 else 1)
        o_bytes = P * (m.opt_state_bytes + m.master_bytes) / opt_shard
        if s.zero_stage >= 2:
            g_bytes /= s.dp
        if s.zero_stage >= 3:
            p_bytes /= s.dp  # params stored sharded between steps
        # activations: batch split over dp x ep, per-microbatch live set
        # over pp stages; remat keeps ~1 residual per layer boundary
        b_local = max(m.global_batch // (s.dp * s.ep
                                         * s.micro_batches), 1)
        act_per_layer = b_local * m.seq_len * m.d_model * m.act_bytes
        layers_local = max(m.n_layers // s.pp, 1)
        live_factor = 2.0 if m.remat else 14.0   # resid vs full act set
        # gpipe keeps micro_batches in flight; 1f1b keeps <= pp;
        # zero_bubble stashes EVERY micro's input AND cotangent until
        # its deferred W sub-tick (pipeline_schedule module doc)
        if s.pp > 1 and s.schedule == "zero_bubble":
            in_flight = 2 * s.micro_batches
        else:
            in_flight = min(s.micro_batches, s.pp)
        a_bytes = act_per_layer * layers_local * live_factor * in_flight \
            / max(s.mp, 1)
        return p_bytes + g_bytes + o_bytes + a_bytes

    # ------------------------------------------------------------- time
    def _bubble_stretch(self, s: Strategy) -> float:
        """Schedule-tick stretch T / active_ticks from the real decode
        formulas: the factor pure compute inflates by when the device
        idles in fill/drain slots."""
        if s.pp <= 1:
            return 1.0
        from .pipeline_schedule import schedule_bubble_ticks
        bubbles, T = schedule_bubble_ticks(
            s.schedule, s.pp, s.virtual_stages, s.micro_batches)
        active = T - bubbles[0]
        return T / max(active, 1)

    def comp_time(self, m: ModelSpec, s: Strategy,
                  efficiency: Optional[float] = None) -> float:
        c = self.cluster
        eff = c.mxu_efficiency if efficiency is None else efficiency
        flops = m.step_flops() / s.degree()
        if s.pp > 1 and s.schedule == "zero_bubble":
            # B and W each replay the stage forward from the stash: one
            # recompute more than the remat baseline
            flops *= (10.0 / 8.0) if m.remat else (8.0 / 6.0)
        return flops / (c.peak_flops * eff) * self._bubble_stretch(s)

    def comm_time(self, m: ModelSpec, s: Strategy) -> float:
        c = self.cluster
        # dp grad sync: allreduce (zero=0) or RS+AG (zero>=1) of the
        # mp/pp-local shard (the ep-sharded expert grads sync over dp
        # at 1/ep size each — same aggregate as dividing by ep here)
        P = float(m.n_params - m.expert_param_elems) \
            + float(m.expert_param_elems) / max(s.ep, 1)
        comm = 0.0
        g_local = P * m.grad_bytes / (s.mp * s.pp)
        if s.dp > 1:
            if s.zero_stage >= 1:
                comm += 2.0 * _shard_xfer_time(g_local, s.dp, c.ici_bw) \
                    + 2.0 * c.collective_latency
            elif s.bucket_size > 0:
                n_buckets = max(1, math.ceil(g_local / s.bucket_size))
                ring = _ring_allreduce_time(g_local, s.dp, c.ici_bw)
                tail = _ring_allreduce_time(
                    min(float(s.bucket_size), g_local), s.dp, c.ici_bw)
                hide = _OVERLAP_WINDOW * self.comp_time(m, s)
                comm += max(tail, ring - hide) \
                    + n_buckets * c.collective_latency
            else:
                comm += _ring_allreduce_time(g_local, s.dp, c.ici_bw) \
                    + m.n_param_tensors * c.collective_latency
        if s.zero_stage >= 3 and s.dp > 1:
            # params stored sharded: all-gather them for fwd AND for the
            # recomputing bwd
            p_local = P * m.param_bytes / (s.mp * s.pp)
            comm += 2.0 * _shard_xfer_time(p_local, s.dp, c.ici_bw)
        # mp: 2 allreduce fwd + 2 bwd per layer of [B_local, S, d] acts
        if s.mp > 1:
            b_local = max(m.global_batch // (s.dp * s.ep), 1)
            act = b_local * m.seq_len * m.d_model * m.act_bytes
            layers_local = max(m.n_layers // s.pp, 1)
            comm += 4.0 * layers_local * (_ring_allreduce_time(
                act, s.mp, c.ici_bw) + c.collective_latency)
        # ep: dispatch + combine all_to_all of the [E, C, d] capacity
        # buffers per layer, fwd + bwd (4 exchanges); an all_to_all
        # moves (ep-1)/ep of the payload off-chip
        if s.ep > 1 and m.moe_experts:
            toks_local = max(m.global_batch // (s.dp * s.ep), 1) \
                * m.seq_len
            slots = m.moe_capacity_factor * m.moe_top_k * toks_local
            a2a = slots * m.d_model * m.act_bytes * (s.ep - 1) / s.ep
            layers_local = max(m.n_layers // s.pp, 1)
            comm += 4.0 * layers_local * (a2a / c.ici_bw
                                          + c.collective_latency)
        # pp: p2p activation sends per microbatch tick (fwd+bwd)
        if s.pp > 1:
            b_micro = max(m.global_batch // (s.dp * s.ep
                                             * s.micro_batches), 1)
            act = b_micro * m.seq_len * m.d_model * m.act_bytes
            comm += 2.0 * s.micro_batches * act / c.ici_bw
        return comm

    def step_time(self, m: ModelSpec, s: Strategy) -> float:
        return self.comp_time(m, s) + self.comm_time(m, s)

    def predicted_mfu(self, m: ModelSpec, s: Strategy) -> float:
        """Useful-FLOPs MFU per chip at the predicted step time (same
        numerator convention as bench.py's measured MFU)."""
        t = self.step_time(m, s)
        return m.useful_flops() / (t * s.degree() * self.cluster.peak_flops)

    # ------------------------------------------------------ calibration
    def calibrate(self, m: ModelSpec, measurements: dict) -> ClusterSpec:
        """Fit cluster terms from a measured run (PR 1 metrics registry
        numbers) and return a NEW ClusterSpec.

        measurements keys:
          strategy           Strategy (or dict of its fields) the
                             measurement ran under; default Strategy()
          step_seconds       measured wall seconds per train step, OR
          mfu                measured useful-FLOPs MFU per chip
          collective_bytes   + collective_seconds: eager wire totals
                             (fits ici_bw = bytes/seconds)

        mxu_efficiency solves comp_time(eff) = t_meas - comm_pred (the
        comp term is linear in 1/eff); clamped to [0.02, 0.95].
        """
        strat = measurements.get("strategy") or Strategy()
        if isinstance(strat, dict):
            strat = Strategy(**{k: v for k, v in strat.items()
                                if k in {f.name for f in
                                         dataclasses.fields(Strategy)}})
        cluster = dataclasses.replace(self.cluster)
        cb = measurements.get("collective_bytes")
        cs = measurements.get("collective_seconds")
        if cb and cs:
            cluster.ici_bw = float(cb) / float(cs)
        cm = CostModel(cluster)
        t_meas = measurements.get("step_seconds")
        if t_meas is None and measurements.get("mfu"):
            t_meas = m.useful_flops() / (
                float(measurements["mfu"]) * strat.degree()
                * cluster.peak_flops)
        if t_meas:
            unit = cm.comp_time(m, strat, efficiency=1.0)
            comp_budget = float(t_meas) - cm.comm_time(m, strat)
            eff = unit / max(comp_budget, unit / 0.95)
            cluster.mxu_efficiency = min(max(eff, 0.02), 0.95)
        return cluster


class StrategyTuner:
    """Brute-force search over mesh factorizations (the reference tuner's
    role, minus the Program rewriting — shardings here are GSPMD specs)."""

    def __init__(self, cluster: Optional[ClusterSpec] = None):
        self.cluster = cluster or ClusterSpec()
        self.cost_model = CostModel(self.cluster)

    def _factorizations(self, n, with_ep=False):
        for dp in range(1, n + 1):
            if n % dp:
                continue
            rest = n // dp
            for mp in range(1, rest + 1):
                if rest % mp:
                    continue
                rest2 = rest // mp
                if not with_ep:
                    yield dp, mp, rest2, 1
                    continue
                for pp in range(1, rest2 + 1):
                    if rest2 % pp:
                        continue
                    yield dp, mp, pp, rest2 // pp

    def search(self, model: ModelSpec, n_devices: Optional[int] = None,
               top_k: int = 1, schedules=("1f1b",), bucket_sizes=(0,),
               zero_stages=(0, 1, 2, 3)):
        n = n_devices or self.cluster.n_devices
        moe = model.moe_experts > 0
        scored = []
        for dp, mp, pp, ep in self._factorizations(n, with_ep=moe):
            if model.n_layers % pp or model.global_batch % (dp * ep):
                continue
            if model.n_heads and (mp > model.n_heads
                                  or model.n_heads % mp):
                continue
            if model.vocab_size % mp:
                continue
            # ep must divide the expert count — an ep that strands a
            # fractional expert per rank is INFEASIBLE, not just slow
            if ep > 1 and (not moe or model.moe_experts % ep):
                continue
            micro_opts = {1} if pp == 1 else {
                mb for mb in (pp, 2 * pp, 4 * pp)
                if model.global_batch % (dp * ep * mb) == 0}
            sched_opts = schedules if pp > 1 else ("1f1b",)
            # bucketed grad reduction exists only on the pure DENSE-DP
            # executor path (hybrid_gpt's grad_bucket_bytes contract —
            # MoE expert leaves are ep-sharded, never plain-dp-psummed):
            # scoring buckets elsewhere would rank a config no executor
            # can run and let a near-tie flip the mesh choice
            buck_opts = bucket_sizes if (dp > 1 and mp == 1
                                         and pp == 1 and ep == 1
                                         and not moe) else (0,)
            for micro in sorted(micro_opts):
                for zero in zero_stages:
                    for sched in sched_opts:
                        for bucket in buck_opts:
                            if bucket and zero >= 1:
                                continue  # RS/AG path, nothing to bucket
                            s = Strategy(dp=dp, mp=mp, pp=pp, ep=ep,
                                         micro_batches=micro,
                                         zero_stage=zero,
                                         schedule=sched,
                                         bucket_size=bucket)
                            mem = self.cost_model.memory_per_device(
                                model, s)
                            if mem > self.cluster.hbm_bytes:
                                continue
                            t = self.cost_model.step_time(model, s)
                            # prefer simpler configs on near-ties (zero
                            # adds collectives; mp/pp/ep/zb add failure
                            # surface)
                            tie_break = (zero, mp, pp, ep,
                                         sched != "1f1b", bucket)
                            scored.append((t, tie_break, s, mem))
        if not scored:
            raise ValueError(
                "no feasible parallel strategy: model does not fit "
                f"{n} x {self.cluster.hbm_bytes / 1e9:.0f}GB devices")
        scored.sort(key=lambda r: (r[0], r[1]))
        if top_k == 1:
            return scored[0][2]
        return [r[2] for r in scored[:top_k]]


@dataclasses.dataclass
class TunedResult:
    """`tune()` output: the chosen strategy plus the prediction that a
    later measured run is checked against (bench.py records
    predicted_mfu next to the measured MFU)."""
    strategy: Strategy
    step_time: float
    predicted_mfu: float
    memory_bytes: float
    cluster: ClusterSpec
    calibrated: bool = False
    candidates: list = dataclasses.field(default_factory=list)


def tune(model: ModelSpec, cluster: Optional[ClusterSpec] = None,
         n_devices: Optional[int] = None, measurements: Optional[dict] = None,
         schedules=("1f1b", "zero_bubble"),
         bucket_sizes=(0, 1 << 24, 1 << 27), top_k=8,
         zero_stages=(0, 1, 2, 3)) -> TunedResult:
    """Measurement-driven placement search: optionally calibrate the
    cluster from a profiled run, then score every (dp, mp, pp, zero,
    micro, schedule, bucket_size) config and return the winner with its
    predicted MFU. Callers whose executor supports only a subset of
    ZeRO stages must pass that subset as `zero_stages` — clamping the
    WINNER after the search would execute a config the HBM-feasibility
    gate never admitted."""
    cluster = cluster or ClusterSpec()
    calibrated = False
    if measurements:
        cluster = CostModel(cluster).calibrate(model, measurements)
        calibrated = True
    tuner = StrategyTuner(cluster)
    ranked = tuner.search(model, n_devices, top_k=max(int(top_k), 2),
                          schedules=schedules, bucket_sizes=bucket_sizes,
                          zero_stages=zero_stages)
    best = ranked[0]
    cm = tuner.cost_model
    return TunedResult(
        strategy=best,
        step_time=cm.step_time(model, best),
        predicted_mfu=cm.predicted_mfu(model, best),
        memory_bytes=cm.memory_per_device(model, best),
        cluster=cluster,
        calibrated=calibrated,
        candidates=ranked)
