"""Elastic training manager.

Parity: `python/paddle/distributed/fleet/elastic/manager.py:127`
(`ElasticManager`: etcd registration :229, watch/scale callbacks :244,
fault-tolerant restart via the launcher).

TPU-native scope: within a slice, chip failure kills the whole SPMD
program — elasticity happens at the JOB level: a watchdog restarts the
training process and the program resumes from the latest (orbax) sharded
checkpoint. This manager implements that restart loop with a file-based
heartbeat/KV (no etcd in-image); the etcd transport can be slotted in via
the same Store interface.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time


class FileStore:
    """KV + heartbeat store on a shared filesystem (etcd stand-in)."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key, value):
        # atomic write: a concurrent alive_nodes() reader must never see a
        # truncated file; the dot prefix keeps in-flight temps out of the
        # heartbeat_* directory listing
        path = os.path.join(self.root, key)
        tmp = os.path.join(self.root, f".{key}.tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def get(self, key, default=None):
        p = os.path.join(self.root, key)
        if not os.path.exists(p):
            return default
        try:
            with open(p) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return default

    def heartbeat(self, node_id):
        self.put(f"heartbeat_{node_id}", {"ts": time.time()})

    def alive_nodes(self, timeout=30.0):
        now = time.time()
        out = []
        for f in os.listdir(self.root):
            if f.startswith("heartbeat_") and ".tmp" not in f:
                hb = self.get(f)
                if hb and now - hb["ts"] < timeout:
                    out.append(f[len("heartbeat_"):])
        return sorted(out)


class KVMasterServer:
    """TCP KV master (the launcher master.py HTTP/etcd-server role): a
    json-line protocol over one listening socket. Second Store transport
    proving the FileStore seam is real."""

    def __init__(self, host="127.0.0.1", port=0):
        import socketserver
        import threading

        kv = {}
        lock = threading.Lock()

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    with lock:
                        if req["op"] == "put":
                            kv[req["key"]] = req["value"]
                            resp = {"ok": True}
                        elif req["op"] == "get":
                            resp = {"ok": True,
                                    "value": kv.get(req["key"])}
                        elif req["op"] == "list":
                            pfx = req.get("prefix", "")
                            resp = {"ok": True,
                                    "items": {k: v for k, v in kv.items()
                                              if k.startswith(pfx)}}
                        else:
                            resp = {"ok": False}
                    self.wfile.write((json.dumps(resp) + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()


class TcpStore:
    """Store client with the same interface as FileStore, over a
    KVMasterServer (PADDLE_ELASTIC_STORE=tcp://host:port)."""

    def __init__(self, host, port):
        import socket
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=30)
        self._rfile = self._sock.makefile("r")

    def _call(self, req):
        self._sock.sendall((json.dumps(req) + "\n").encode())
        return json.loads(self._rfile.readline())

    def put(self, key, value):
        self._call({"op": "put", "key": key, "value": value})

    def get(self, key, default=None):
        resp = self._call({"op": "get", "key": key})
        v = resp.get("value")
        return default if v is None else v

    def heartbeat(self, node_id):
        self.put(f"heartbeat_{node_id}", {"ts": time.time()})

    def alive_nodes(self, timeout=30.0):
        now = time.time()
        items = self._call({"op": "list",
                            "prefix": "heartbeat_"}).get("items", {})
        return sorted(k[len("heartbeat_"):] for k, v in items.items()
                      if v and now - v["ts"] < timeout)


def make_store(spec):
    """'tcp://host:port' -> TcpStore; anything else -> FileStore root."""
    if spec.startswith("tcp://"):
        host, port = spec[len("tcp://"):].rsplit(":", 1)
        return TcpStore(host, port)
    return FileStore(spec)


class ElasticManager:
    """manager.py:127 parity: node registration (:229), membership
    watch + scale in/out with RANK REGENERATION (:244), fault-tolerant
    restart. On a membership change the leader (lowest alive node id)
    publishes a new `generation` {gen, nodes}; every node kills its
    training process and relaunches it with regenerated ranks
    (NODE_RANK = index in the sorted alive set, PADDLE_NNODES = world);
    nodes scaled out of the membership exit cleanly."""

    def __init__(self, args=None, store_root=None, max_restarts=3,
                 heartbeat_interval=5.0, min_nodes=1, max_nodes=None,
                 settle_checks=2):
        self.store = make_store(store_root or
                                os.environ.get("PADDLE_ELASTIC_STORE",
                                               "/tmp/paddle_tpu_elastic"))
        self.max_restarts = max_restarts
        self.heartbeat_interval = heartbeat_interval
        self.node_id = os.environ.get("PADDLE_NODE_RANK", "0")
        self.min_nodes = int(os.environ.get("PADDLE_ELASTIC_MIN_NODES",
                                            min_nodes))
        self.max_nodes = max_nodes
        self.settle_checks = settle_checks
        self.restarts = 0

    def register(self):
        """manager.py:229 parity: announce this node."""
        self.store.heartbeat(self.node_id)
        self.store.put(f"node_{self.node_id}",
                       {"pid": os.getpid(), "restarts": self.restarts})

    def watch(self):
        return self.store.alive_nodes(timeout=self.heartbeat_interval * 4)

    # ---- scale in/out ----------------------------------------------
    def _generation(self):
        return self.store.get("generation") or {"gen": 0, "nodes": []}

    def _maybe_bump_generation(self, pending):
        """Leader duty (lowest alive id): after the membership has
        differed from the current generation for `settle_checks`
        consecutive watches (debounce), publish gen+1 with the new
        node list. Returns the updated pending counter."""
        alive = self.watch()
        if not alive or alive[0] != self.node_id:
            return 0
        gen = self._generation()
        if self.max_nodes:
            alive = alive[:self.max_nodes]
        if alive == gen["nodes"] or len(alive) < self.min_nodes:
            return 0
        pending += 1
        if pending >= self.settle_checks:
            self.store.put("generation",
                           {"gen": gen["gen"] + 1, "nodes": alive})
            sys.stderr.write(
                f"[elastic] scale event: gen {gen['gen'] + 1} "
                f"nodes {alive}\n")
            return 0
        return pending

    def _spawn(self, cmd, gen):
        """Relaunch training with REGENERATED ranks for this
        generation (manager.py scale in/out -> launcher restart)."""
        env = dict(os.environ)
        nodes = gen["nodes"]
        env["PADDLE_NNODES"] = str(len(nodes))
        env["PADDLE_TRAINERS_NUM"] = str(len(nodes))
        env["NODE_RANK"] = str(nodes.index(self.node_id))
        env["PADDLE_NODE_RANK"] = env["NODE_RANK"]
        env["PADDLE_ELASTIC_GEN"] = str(gen["gen"])
        return subprocess.Popen(cmd, env=env)

    def _stop_proc(self, proc, grace=30.0):
        """Terminate the training process, heartbeating WHILE waiting
        (a graceful shutdown longer than the aliveness window must not
        make this node look dead); SIGKILL past the grace period."""
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        deadline = time.time() + grace
        while proc.poll() is None and time.time() < deadline:
            self.store.heartbeat(self.node_id)
            time.sleep(min(self.heartbeat_interval, 0.5))
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    def _handle_exit(self, returncode):
        """-> "done" | "give-up" | "restart" (shared restart
        bookkeeping for the watchdog and elastic loops)."""
        if returncode == 0:
            return "done"
        self.restarts += 1
        if self.restarts > self.max_restarts:
            return "give-up"
        sys.stderr.write(
            f"[elastic] training exited {returncode}; restart "
            f"{self.restarts}/{self.max_restarts}\n")
        return "restart"

    def run(self, cmd, elastic=False, poll_timeout=None):
        """Supervise `cmd` (the training script).

        elastic=False: plain fault-tolerant restart (watchdog).
        elastic=True: additionally watch membership; on a scale event
        every surviving node restarts `cmd` with regenerated ranks, and
        a node dropped from the membership returns "scaled-in".
        `poll_timeout` bounds either loop (tests)."""
        deadline = time.time() + poll_timeout if poll_timeout else None
        if not elastic:
            while True:
                self.register()
                proc = subprocess.Popen(cmd)
                while proc.poll() is None:
                    if deadline and time.time() > deadline:
                        self._stop_proc(proc)
                        return "timeout"
                    self.store.heartbeat(self.node_id)
                    time.sleep(self.heartbeat_interval)
                verdict = self._handle_exit(proc.returncode)
                if verdict == "done":
                    return 0
                if verdict == "give-up":
                    return proc.returncode

        self.register()
        my_gen = -1
        proc = None
        pending = 0
        try:
            while True:
                if deadline and time.time() > deadline:
                    self._stop_proc(proc)
                    return "timeout"
                self.store.heartbeat(self.node_id)
                pending = self._maybe_bump_generation(pending)
                gen = self._generation()
                if gen["gen"] != my_gen and gen["nodes"]:
                    if self.node_id not in gen["nodes"]:
                        if my_gen == -1:
                            if self.max_nodes and \
                                    len(gen["nodes"]) >= self.max_nodes:
                                alive = set(self.watch())
                                if all(n in alive
                                       for n in gen["nodes"]):
                                    # cluster full of LIVE nodes: no
                                    # slot is coming — don't spin
                                    # forever. (A dead member means a
                                    # reshuffle is imminent; keep
                                    # waiting to replace it.)
                                    return "not-admitted"
                            # joining node: keep heartbeating until the
                            # leader includes us in a future generation
                            time.sleep(self.heartbeat_interval)
                            continue
                        self._stop_proc(proc)
                        return "scaled-in"
                    self._stop_proc(proc)
                    my_gen = gen["gen"]
                    self.restarts = 0
                    proc = self._spawn(cmd, gen)
                elif proc is not None and proc.poll() is not None:
                    verdict = self._handle_exit(proc.returncode)
                    if verdict == "done":
                        return 0
                    if verdict == "give-up":
                        return proc.returncode
                    # respawn from the ALREADY-VALIDATED generation (a
                    # fresh read could exclude this node mid-loop)
                    proc = self._spawn(cmd, gen)
                time.sleep(self.heartbeat_interval)
        finally:
            if proc is not None and proc.poll() is None:
                proc.terminate()
