"""Elastic training manager.

Parity: `python/paddle/distributed/fleet/elastic/manager.py:127`
(`ElasticManager`: etcd registration :229, watch/scale callbacks :244,
fault-tolerant restart via the launcher).

TPU-native scope: within a slice, chip failure kills the whole SPMD
program — elasticity happens at the JOB level: a watchdog restarts the
training process and the program resumes from the latest (orbax) sharded
checkpoint. This manager implements that restart loop with a file-based
heartbeat/KV (no etcd in-image); the etcd transport can be slotted in via
the same Store interface.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time


class FileStore:
    """KV + heartbeat store on a shared filesystem (etcd stand-in)."""

    def __init__(self, root):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, key, value):
        # atomic write: a concurrent alive_nodes() reader must never see a
        # truncated file; the dot prefix keeps in-flight temps out of the
        # heartbeat_* directory listing
        path = os.path.join(self.root, key)
        tmp = os.path.join(self.root, f".{key}.tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.replace(tmp, path)

    def get(self, key, default=None):
        p = os.path.join(self.root, key)
        if not os.path.exists(p):
            return default
        try:
            with open(p) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return default

    def heartbeat(self, node_id):
        self.put(f"heartbeat_{node_id}", {"ts": time.time()})

    def alive_nodes(self, timeout=30.0):
        now = time.time()
        out = []
        for f in os.listdir(self.root):
            if f.startswith("heartbeat_") and ".tmp" not in f:
                hb = self.get(f)
                if hb and now - hb["ts"] < timeout:
                    out.append(f[len("heartbeat_"):])
        return sorted(out)


class ElasticManager:
    def __init__(self, args=None, store_root=None, max_restarts=3,
                 heartbeat_interval=5.0):
        self.store = FileStore(store_root or
                               os.environ.get("PADDLE_ELASTIC_STORE",
                                              "/tmp/paddle_tpu_elastic"))
        self.max_restarts = max_restarts
        self.heartbeat_interval = heartbeat_interval
        self.node_id = os.environ.get("PADDLE_NODE_RANK", "0")
        self.restarts = 0

    def register(self):
        """manager.py:229 parity: announce this node."""
        self.store.heartbeat(self.node_id)
        self.store.put(f"node_{self.node_id}",
                       {"pid": os.getpid(), "restarts": self.restarts})

    def watch(self):
        return self.store.alive_nodes(timeout=self.heartbeat_interval * 4)

    def run(self, cmd):
        """Supervise `cmd` (the training script); restart on failure up to
        max_restarts (the launcher watchdog capability)."""
        while True:
            self.register()
            proc = subprocess.Popen(cmd)
            while proc.poll() is None:
                self.store.heartbeat(self.node_id)
                time.sleep(self.heartbeat_interval)
            if proc.returncode == 0:
                return 0
            self.restarts += 1
            if self.restarts > self.max_restarts:
                return proc.returncode
            sys.stderr.write(
                f"[elastic] training exited {proc.returncode}; "
                f"restart {self.restarts}/{self.max_restarts}\n")
